"""Schedule verification — the `happens_before` upgrade the reference's
own tests ask for.

The reference's schedule tests check instruction *presence and coarse
ordering* and say so honestly: "these tests are weak [...] a
happens_before predicate would be the upgrade"
(`/root/reference/tests/test_schedules.py:4-10`). This module IS that
upgrade: it executes all stages' instruction streams against channel
semantics (activations flow right, cotangents flow left, FIFO per edge)
and proves, for any (num_stages, num_micro_batches):

- **deadlock-freedom**: every Recv is eventually satisfiable — the
  schedule can run to completion under blocking channels;
- **data correctness**: each Forward consumes the activation of ITS
  microbatch (channel tags must match — a schedule that reorders sends
  is caught, not just one that forgets them); each Backward consumes the
  matching cotangent and a stashed forward that exists and is used
  exactly once;
- **reduction placement**: exactly one BackwardGradAllReduce per stage
  per batch, as that stage's final backward, after ZeroGrad and before
  OptimizerStep (the reference's interleaved-DDP contract,
  `pipe.py:302-327`);
- **memory bounds**: the simulator measures each stage's PEAK activation
  stash, so 1F1B's min(num_stages - stage_id, n_mu) claim is checked,
  not asserted;
- **makespan**: unit-cost compute rounds give each schedule's bubble — a
  quantitative schedule-research metric (Naive >> GPipe ≈ 1F1B).

Pure Python over pure-data schedules: no devices, no arrays — the same
zero-process testability the schedule layer was designed for.
"""

from __future__ import annotations

from dataclasses import dataclass, field

from shallowspeed_tpu.parallel.instructions import (
    BackwardGradAcc,
    BackwardGradAllReduce,
    Forward,
    LoadMuBatchInput,
    LoadMuBatchTarget,
    OptimizerStep,
    RecvActivations,
    RecvOutputGrad,
    SendActivations,
    SendInputGrad,
    ZeroGrad,
)

_COMPUTE = (Forward, BackwardGradAcc, BackwardGradAllReduce)


class ScheduleError(AssertionError):
    """A schedule violated channel semantics or a pipeline invariant."""


@dataclass
class SimReport:
    """What the simulator proved/measured for one schedule instance."""

    makespan: int                      # unit-cost compute rounds to drain
    peak_stash: list                   # per-stage peak in-flight forwards
    fwd_rounds: dict = field(default_factory=dict)   # (stage, mu) -> round
    bwd_rounds: dict = field(default_factory=dict)


def _flatten(schedule) -> list:
    return [cmd for step in schedule.steps() for cmd in step]


def simulate(schedule_cls, num_micro_batches: int, num_stages: int,
             training: bool = True) -> SimReport:
    """Run every stage's instruction stream against FIFO channel
    semantics; raise ScheduleError on any violation (see module
    docstring for the list). `training=False` relaxes the
    backward/reduction invariants (inference schedules)."""
    n_mu = num_micro_batches
    progs = [_flatten(schedule_cls(n_mu, num_stages, s))
             for s in range(num_stages)]
    pc = [0] * num_stages
    # channels keyed by receiving stage; values are microbatch tags
    act_ch = [[] for _ in range(num_stages)]    # from stage s-1
    grad_ch = [[] for _ in range(num_stages)]   # from stage s+1
    bufs = [{} for _ in range(num_stages)]      # buffer_id -> mu tag
    stash = [set() for _ in range(num_stages)]  # forwards awaiting bwd
    peak = [0] * num_stages
    fwd_done = [set() for _ in range(num_stages)]
    bwd_done = [set() for _ in range(num_stages)]
    allreduce_seen = [False] * num_stages
    zerograd_seen = [False] * num_stages
    opt_seen = [False] * num_stages
    report = SimReport(0, peak)

    def err(s, msg):
        raise ScheduleError(
            f"stage {s}/{num_stages}, n_mu={n_mu}, "
            f"pc={pc[s]} ({progs[s][pc[s]] if pc[s] < len(progs[s]) else 'end'}): {msg}")

    def runnable(s):
        if pc[s] >= len(progs[s]):
            return False
        cmd = progs[s][pc[s]]
        if isinstance(cmd, RecvActivations):
            return bool(act_ch[s])
        if isinstance(cmd, RecvOutputGrad):
            return bool(grad_ch[s])
        return True

    def execute(s):
        cmd = progs[s][pc[s]]
        if isinstance(cmd, ZeroGrad):
            if fwd_done[s] or bwd_done[s]:
                err(s, "ZeroGrad after compute began")
            zerograd_seen[s] = True
        elif isinstance(cmd, LoadMuBatchInput):
            if s != 0:
                err(s, "LoadMuBatchInput on a non-first stage")
            bufs[s][cmd.buffer_id] = cmd.mubatch_id
        elif isinstance(cmd, LoadMuBatchTarget):
            if s != num_stages - 1:
                err(s, "LoadMuBatchTarget on a non-last stage")
            bufs[s][cmd.buffer_id] = cmd.mubatch_id
        elif isinstance(cmd, RecvActivations):
            bufs[s][cmd.buffer_id] = act_ch[s].pop(0)
        elif isinstance(cmd, RecvOutputGrad):
            bufs[s][cmd.buffer_id] = grad_ch[s].pop(0)
        elif isinstance(cmd, Forward):
            got = bufs[s].get(cmd.buffer_id)
            if got != cmd.mubatch_id:
                err(s, f"Forward(mu={cmd.mubatch_id}) consumed the "
                       f"activation of mu={got}")
            if cmd.mubatch_id in fwd_done[s]:
                err(s, f"second Forward of mu={cmd.mubatch_id}")
            fwd_done[s].add(cmd.mubatch_id)
            if training:
                stash[s].add(cmd.mubatch_id)
                peak[s] = max(peak[s], len(stash[s]))
            report.fwd_rounds[(s, cmd.mubatch_id)] = report.makespan
        elif isinstance(cmd, SendActivations):
            if s == num_stages - 1:
                err(s, "SendActivations off the pipeline's last stage")
            act_ch[s + 1].append(bufs[s].get(cmd.buffer_id))
        elif isinstance(cmd, (BackwardGradAcc, BackwardGradAllReduce)):
            got = bufs[s].get(cmd.buffer_id)
            if got != cmd.mubatch_id:
                err(s, f"Backward(mu={cmd.mubatch_id}) consumed the "
                       f"cotangent of mu={got}")
            if cmd.mubatch_id not in stash[s]:
                err(s, f"Backward(mu={cmd.mubatch_id}) without a stashed "
                       f"forward (missing, or consumed twice)")
            stash[s].remove(cmd.mubatch_id)
            bwd_done[s].add(cmd.mubatch_id)
            report.bwd_rounds[(s, cmd.mubatch_id)] = report.makespan
            if isinstance(cmd, BackwardGradAllReduce):
                if allreduce_seen[s]:
                    err(s, "second BackwardGradAllReduce in one batch")
                allreduce_seen[s] = True
            elif allreduce_seen[s]:
                err(s, "BackwardGradAcc AFTER the all-reduce backward "
                       "(its gradient would miss the DP reduction)")
        elif isinstance(cmd, SendInputGrad):
            if s == 0:
                err(s, "SendInputGrad off the pipeline's first stage")
            grad_ch[s - 1].append(bufs[s].get(cmd.buffer_id))
        elif isinstance(cmd, OptimizerStep):
            if len(bwd_done[s]) != n_mu:
                err(s, f"OptimizerStep after only {len(bwd_done[s])}/"
                       f"{n_mu} backwards")
            if not allreduce_seen[s]:
                err(s, "OptimizerStep without a DP all-reduce backward")
            opt_seen[s] = True
        else:
            err(s, f"unknown instruction {cmd}")
        pc[s] += 1

    # round-based: every stage executes zero-cost instructions freely and
    # at most ONE compute instruction per round (unit-cost model)
    while any(pc[s] < len(progs[s]) for s in range(num_stages)):
        progressed = False
        for s in range(num_stages):
            computed = False
            while runnable(s) and not computed:
                computed = isinstance(progs[s][pc[s]], _COMPUTE)
                execute(s)
                progressed = True
        if not progressed:
            stuck = [(s, str(progs[s][pc[s]]))
                     for s in range(num_stages) if pc[s] < len(progs[s])]
            raise ScheduleError(
                f"deadlock with n_mu={n_mu}, stages={num_stages}: every "
                f"remaining stage is blocked on a Recv: {stuck}")
        report.makespan += 1

    for s in range(num_stages):
        if act_ch[s] or grad_ch[s]:
            err(s, f"undelivered messages at drain: act={act_ch[s]} "
                   f"grad={grad_ch[s]}")
        if fwd_done[s] != set(range(n_mu)):
            err(s, f"forwards run: {sorted(fwd_done[s])} != all {n_mu}")
        if training:
            if bwd_done[s] != set(range(n_mu)):
                err(s, f"backwards run: {sorted(bwd_done[s])}")
            if not (zerograd_seen[s] and opt_seen[s]):
                err(s, "missing ZeroGrad/OptimizerStep bracket")
    # cross-stage happens-before: stage s+1's forward of mu cannot precede
    # stage s's (tags already prove data flow; this proves the timing)
    for (s, mu), r in report.fwd_rounds.items():
        if s + 1 < num_stages:
            nxt = report.fwd_rounds[(s + 1, mu)]
            if nxt < r:
                raise ScheduleError(
                    f"FWD({s + 1}, {mu}) at round {nxt} precedes "
                    f"FWD({s}, {mu}) at round {r}")
    return report


# public-API alias (`shallowspeed_tpu.simulate_schedule`): the package
# namespace needs a name that says what is simulated
simulate_schedule = simulate


# ------------------------------------------- interleaved 1F1B (virtual)


@dataclass
class InterleavedReport:
    """Device-level simulation result for interleaved 1F1B."""

    makespan: int            # chunk-unit rounds (one chunk = 1 unit)
    plain_makespan: int      # plain 1F1B at depth pp, scaled to chunk units
    peak_stash: list         # per-DEVICE peak in-flight forward stashes
    logical: SimReport       # full channel-semantics proof at depth pp*vpp


def simulate_interleaved(num_micro_batches: int, pp: int,
                         vpp: int) -> InterleavedReport:
    """Interleaved (virtual-stage) 1F1B — Megatron-style: device d hosts
    logical stages {d, d+pp, ..., d+(vpp-1)pp}, each running the plain
    1F1B instruction stream at logical depth pp*vpp.

    Two-level proof:
    - the LOGICAL pipeline is verified with full channel semantics by
      `simulate` (deadlock-freedom, tag-matched dataflow, per-logical-
      stage stash bound) — interleaving changes device placement, not
      the streams;
    - this function then list-schedules those verified streams under
      DEVICE contention (each device executes at most one chunk-compute
      per round; drain-first priority: a ready backward beats a ready
      forward, matching 1F1B's memory discipline) and measures the real
      makespan in chunk units plus each device's aggregate stash peak.

    The interleaving win: plain 1F1B's bubble is (pp-1) FULL-stage units
    while the virtual schedule's is (pp*vpp-1) CHUNK units = (pp-1) + a
    vpp-fraction — `makespan < plain_makespan` for n_mu >= pp (asserted
    in tests, reported here).
    """
    from shallowspeed_tpu.parallel.schedules import PipeDreamSchedule

    n_mu = num_micro_batches
    depth = pp * vpp
    logical = simulate(PipeDreamSchedule, n_mu, depth)
    plain = simulate(PipeDreamSchedule, n_mu, pp)
    _, _, _, peak, rounds = _greedy_interleaved(n_mu, pp, vpp)

    return InterleavedReport(
        makespan=rounds,
        plain_makespan=plain.makespan * vpp,
        peak_stash=peak,
        logical=logical,
    )


@dataclass
class InterleavedTables:
    """The greedy interleaved-1F1B schedule lowered to STATIC per-round
    arrays a compiled `lax.scan` can follow (pipeline_lm's vpp x 1f1b
    engine). Round semantics: each device executes at most ONE chunk op
    (op[r, d]: 0 none, 1 F, 2 B) on chunk `chunk[r, d]`, microbatch
    `mu[r, d]`; afterwards activations hop one step right and cotangents
    one step left (both unconditional ppermutes), and each device writes
    the arrival into `act_write`/`grad_write` (the trash slot — index ==
    n_*_slots — absorbs rounds with no valid arrival, keeping the
    program uniform). F reads its input from `act_read` and stashes it
    at `stash_write`; B re-reads the stash at `stash_read` and its
    incoming cotangent at `grad_read`. Slot indices come from greedy
    interval coloring of message/stash lifetimes, so n_*_slots is the
    measured peak concurrency, not a guess."""

    n_rounds: int
    n_act_slots: int
    n_grad_slots: int
    n_stash_slots: int
    op: "object"          # all arrays: int32 (n_rounds, pp)
    chunk: "object"
    mu: "object"
    act_read: "object"
    act_write: "object"
    grad_read: "object"
    grad_write: "object"
    stash_write: "object"
    stash_read: "object"


def _greedy_interleaved(n_mu: int, pp: int, vpp: int):
    """The device-contention list scheduling `simulate_interleaved`
    measures, with full per-op placement recorded: returns
    (ops, f_round, b_round, peak, rounds) where
    ops[(r, d)] = ("F"|"B", l, mu)."""
    depth = pp * vpp

    def stream(stage):
        s_ops = []
        warm = min(depth - stage - 1, n_mu)
        s_ops += [("F", m) for m in range(warm)]
        for i in range(n_mu - warm):
            s_ops += [("F", warm + i), ("B", i)]
        s_ops += [("B", m) for m in range(n_mu - warm, n_mu)]
        return s_ops

    streams = {ls: stream(ls) for ls in range(depth)}
    pos = {ls: 0 for ls in range(depth)}
    f_round, b_round = {}, {}
    stash = [0] * pp
    peak = [0] * pp
    ops = {}
    rounds = 0
    total = sum(len(s) for s in streams.values())
    done = 0

    def ready(ls, rnd):
        if pos[ls] >= len(streams[ls]):
            return False
        op, mu = streams[ls][pos[ls]]
        if op == "F":
            return ls == 0 or f_round.get((ls - 1, mu), rnd) < rnd
        return (f_round.get((ls, mu), rnd) < rnd
                and (ls == depth - 1
                     or b_round.get((ls + 1, mu), rnd) < rnd))

    while done < total:
        progressed = False
        for d in range(pp):
            cands = [ls for ls in range(d, depth, pp) if ready(ls, rounds)]
            if not cands:
                continue

            def prio(ls):
                op, mu = streams[ls][pos[ls]]
                return (0 if op == "B" else 1, -ls, mu)

            ls = min(cands, key=prio)
            op, mu = streams[ls][pos[ls]]
            if op == "F":
                f_round[(ls, mu)] = rounds
                stash[d] += 1
                peak[d] = max(peak[d], stash[d])
            else:
                b_round[(ls, mu)] = rounds
                stash[d] -= 1
            ops[(rounds, d)] = (op, ls, mu)
            pos[ls] += 1
            done += 1
            progressed = True
        rounds += 1
        if not progressed and done < total:
            raise ScheduleError(
                f"interleaved schedule wedged at round {rounds} "
                f"(pp={pp}, vpp={vpp}, n_mu={n_mu})")
    return ops, f_round, b_round, peak, rounds


def _color_intervals(items):
    """items: list of (key, write_round, read_round). Greedy interval
    coloring: two items share a slot iff the earlier one's read is <=
    the later one's write (a slot read during round r may be rewritten
    at the end of round r' >= r; writes and reads of one device never
    collide within a round — one op per round). Returns ({key: slot},
    n_slots)."""
    slots_free_at = []     # per slot: round after which it is reusable
    assign = {}
    for key, w, r in sorted(items, key=lambda it: (it[1], it[2])):
        for i, free in enumerate(slots_free_at):
            if free <= w:
                assign[key] = i
                slots_free_at[i] = r
                break
        else:
            assign[key] = len(slots_free_at)
            slots_free_at.append(r)
    return assign, len(slots_free_at)


def interleaved_tables(num_micro_batches: int, pp: int,
                       vpp: int) -> InterleavedTables:
    """Lower the verified greedy interleaved-1F1B schedule to the static
    per-round tables the compiled engine follows (see InterleavedTables).
    The same scheduling core backs `simulate_interleaved`, so what the
    engine executes IS what the simulator proves."""
    import numpy as np

    n_mu = num_micro_batches
    depth = pp * vpp
    ops, f_round, b_round, _peak, rounds = _greedy_interleaved(
        n_mu, pp, vpp)

    # ---- message lifetimes, per consumer device
    act_msgs = [[] for _ in range(pp)]   # (key=(l+1, mu), write, read)
    grad_msgs = [[] for _ in range(pp)]
    for (ls, mu), r_p in f_round.items():
        if ls == depth - 1:
            continue                     # last logical stage: loss, no msg
        r_c = f_round[(ls + 1, mu)]
        act_msgs[(ls + 1) % pp].append(((ls + 1, mu), r_p, r_c))
    for (ls, mu), r_p in b_round.items():
        if ls == 0:
            continue                     # stage 0's dx is discarded
        r_c = b_round[(ls - 1, mu)]
        grad_msgs[(ls - 1) % pp].append(((ls - 1, mu), r_p, r_c))
    stash_items = [[] for _ in range(pp)]  # (key=(l, mu), F round, B round)
    for (ls, mu), r_f in f_round.items():
        stash_items[ls % pp].append(((ls, mu), r_f, b_round[(ls, mu)]))

    act_assign, grad_assign, stash_assign = {}, {}, {}
    n_act = n_grad = n_stash = 0
    for d in range(pp):
        a, na = _color_intervals(act_msgs[d])
        g, ng = _color_intervals(grad_msgs[d])
        st, ns = _color_intervals(stash_items[d])
        act_assign.update(a)
        grad_assign.update(g)
        stash_assign.update(st)
        n_act, n_grad, n_stash = (max(n_act, na), max(n_grad, ng),
                                  max(n_stash, ns))

    # ---- per-round tables (trash slot = n_*_slots)
    op_t = np.zeros((rounds, pp), np.int32)
    chunk_t = np.zeros((rounds, pp), np.int32)
    mu_t = np.zeros((rounds, pp), np.int32)
    act_r = np.full((rounds, pp), n_act, np.int32)
    act_w = np.full((rounds, pp), n_act, np.int32)
    grad_r = np.full((rounds, pp), n_grad, np.int32)
    grad_w = np.full((rounds, pp), n_grad, np.int32)
    stash_w = np.full((rounds, pp), n_stash, np.int32)
    stash_r = np.full((rounds, pp), n_stash, np.int32)
    for (r, d), (op, ls, mu) in ops.items():
        v = ls // pp
        assert ls % pp == d
        op_t[r, d] = 1 if op == "F" else 2
        chunk_t[r, d] = v
        mu_t[r, d] = mu
        if op == "F":
            if ls > 0:
                act_r[r, d] = act_assign[(ls, mu)]
            stash_w[r, d] = stash_assign[(ls, mu)]
            # the produced activation arrives at device (d+1) % pp at
            # the END of this round; that device writes it to the
            # message's colored slot
            if ls < depth - 1:
                act_w[r, (d + 1) % pp] = act_assign[(ls + 1, mu)]
        else:
            if ls < depth - 1:
                grad_r[r, d] = grad_assign[(ls, mu)]
            stash_r[r, d] = stash_assign[(ls, mu)]
            if ls > 0:
                grad_w[r, (d - 1) % pp] = grad_assign[(ls - 1, mu)]

    return InterleavedTables(
        n_rounds=rounds, n_act_slots=n_act, n_grad_slots=n_grad,
        n_stash_slots=n_stash, op=op_t, chunk=chunk_t, mu=mu_t,
        act_read=act_r, act_write=act_w, grad_read=grad_r,
        grad_write=grad_w, stash_write=stash_w, stash_read=stash_r)


# ------------------------------------------------- zero-bubble (ZB-H1)


@dataclass
class ZBReport:
    """Zero-bubble-H1 vs 1F1B, costed device-level list scheduling."""

    makespan: int          # ZB-H1 rounds (F=1, B=1, W=1)
    f1b1_makespan: int     # plain 1F1B rounds (F=1, full backward=2)
    bubble: int            # ZB idle rounds inside the busy window, worst device
    f1b1_bubble: int
    peak_stash: list       # per-device peak (act stashes + W-pending stashes)
    op_rounds: dict = field(default_factory=dict)
    # ("F"|"B"|"W", stage, mu) -> START round of the ZB-H1 schedule
    # (the renderer's feed — plot_schedule draws what was verified)


def simulate_zb(num_micro_batches: int, pp: int) -> ZBReport:
    """ZB-H1 (Qi et al., "Zero Bubble Pipeline Parallelism"):
    the backward splits into B (activation cotangent, needed by the
    UPSTREAM stage — on the critical path) and W (weight gradients,
    needed only by this stage's optimizer step — deferrable). Filling
    pipeline bubbles with deferred W work removes most of 1F1B's drain
    bubble at equal total compute.

    Cost model: F = 1 round, B = 1, W = 1 (the full backward = B + W =
    2, matching the 1F1B comparison where the fused backward costs 2
    rounds). Dependencies: F(l,m) after F(l-1,m); B(l,m) after F(l,m)
    and B(l+1,m); W(l,m) after B(l,m), all before the stage's
    OptimizerStep (= end of batch here). Greedy device-level list
    scheduling with the ZB-H1 priority B > F > W (W only fills holes);
    both schedules run through the SAME scheduler so the comparison is
    cost-for-cost.

    Returns makespans, per-device busy-window bubbles, and the measured
    peak stash: F->B activation stashes plus B->W pending-cotangent
    stashes (ZB trades the smaller 1F1B stash for bubble removal —
    the memory cost is reported, not hidden)."""
    n_mu = num_micro_batches

    def run(split_bw: bool):
        # op = ("F"|"B"|"W", l, m); done round recorded at COMPLETION
        cost = {"F": 1, "B": 2, "W": 0}
        if split_bw:
            cost = {"F": 1, "B": 1, "W": 1}
        done = {}
        starts = {}
        pending = set()
        for l in range(pp):
            for m in range(n_mu):
                pending.add(("F", l, m))
                pending.add(("B", l, m))
                if split_bw:
                    pending.add(("W", l, m))
        busy_until = [0] * pp
        first_busy = [None] * pp
        work_rounds = [0] * pp
        stash = [0] * pp
        peak = [0] * pp
        rounds = 0

        def ready(op, rnd):
            kind, l, m = op
            if kind == "F":
                return l == 0 or done.get(("F", l - 1, m), rnd) < rnd
            if kind == "B":
                if ("F", l, m) not in done or done[("F", l, m)] >= rnd:
                    return False
                return l == pp - 1 or done.get(("B", l + 1, m),
                                               rnd) < rnd
            return ("B", l, m) in done and done[("B", l, m)] < rnd

        while pending:
            progressed = False
            for d in range(pp):
                if busy_until[d] > rounds:
                    continue
                cands = [op for op in pending
                         if op[1] == d and ready(op, rounds)]
                if not cands:
                    continue
                # ZB-H1 priority: B first (critical path), W fills
                # holes — EXCEPT when the stash has reached the 1F1B
                # bound, where W jumps ahead of F so memory stays at
                # 1F1B's level (the paper's H1 memory contract)
                if split_bw and stash[d] >= min(pp, n_mu):
                    prio = {"B": 0, "W": 1, "F": 2}
                else:
                    prio = {"B": 0, "F": 1, "W": 2}
                op = min(cands, key=lambda o: (prio[o[0]], o[2]))
                kind, l, m = op
                c = cost[kind]
                busy_until[d] = rounds + c
                done[op] = rounds + c - 1
                starts[op] = rounds
                pending.discard(op)
                if first_busy[d] is None:
                    first_busy[d] = rounds
                work_rounds[d] += c
                if kind == "F":
                    stash[d] += 1          # activation stash F -> B
                elif kind == "B":
                    if split_bw:
                        stash[d] += 1      # cotangent stash B -> W
                        stash[d] -= 1      # activation stash released
                    else:
                        stash[d] -= 1
                else:
                    stash[d] -= 1          # W consumes its stash
                peak[d] = max(peak[d], stash[d])
                progressed = True
            rounds += 1
            if not progressed and pending and \
                    all(busy_until[d] <= rounds - 1 for d in range(pp)):
                raise ScheduleError(
                    f"zero-bubble schedule wedged (pp={pp}, "
                    f"n_mu={n_mu}, split={split_bw})")
        makespan = max(done[op] for op in done) + 1
        bubble = max(
            (makespan - (first_busy[d] or 0)) - work_rounds[d]
            for d in range(pp))
        return makespan, bubble, peak, starts

    zb_makespan, zb_bubble, zb_peak, zb_starts = run(True)
    f_makespan, f_bubble, _, _ = run(False)
    return ZBReport(makespan=zb_makespan, f1b1_makespan=f_makespan,
                    bubble=zb_bubble, f1b1_bubble=f_bubble,
                    peak_stash=zb_peak, op_rounds=zb_starts)


@dataclass
class ZBTables:
    """The verified ZB-H1 schedule lowered to STATIC per-round arrays a
    compiled `lax.scan` follows (pipeline_lm's schedule="zb" engine) —
    the same schedule-as-data lowering `interleaved_tables` does for
    vpp x 1f1b, extended with the W op and its two extra stash pools.

    Round semantics: each device executes at most ONE op per round
    (op[r, d]: 0 idle, 1 F, 2 B, 3 W) on microbatch mu[r, d]; afterwards
    activations hop right and cotangents hop left (unconditional
    ppermutes), arrivals routed via act_write/grad_write (trash slot =
    n_*_slots absorbs empty rounds). Stash pools, all same-device:

    - resb (written at F, read at B): the residuals only the input-
      cotangent pass needs (q/k/v, attention out + lse, norm stats,
      block inputs) — freed as soon as B runs;
    - resw (written at F, read at W): the per-matmul INPUT activations
      the weight-gradient pass needs (h1, a, h2, ffn pre-acts) — live
      until W;
    - tap (written at B, read at W): the per-matmul OUTPUT cotangents B
      peels off while walking the chain.

    Slot counts come from greedy interval coloring of the verified
    schedule's lifetimes, so they are measured peaks, not guesses."""

    n_rounds: int
    n_act_slots: int
    n_grad_slots: int
    n_resb_slots: int
    n_resw_slots: int
    n_tap_slots: int
    op: "object"          # all arrays: int32 (n_rounds, pp)
    mu: "object"
    act_read: "object"
    act_write: "object"
    grad_read: "object"
    grad_write: "object"
    resb_write: "object"
    resb_read: "object"
    resw_write: "object"
    resw_read: "object"      # read by W
    resw_read_b: "object"    # read by B (o / ffn pre-acts feed both passes)
    tap_write: "object"
    tap_read: "object"


def zb_tables(num_micro_batches: int, pp: int) -> ZBTables:
    """Lower the ZB-H1 schedule `simulate_zb` verifies into the static
    per-round tables the compiled engine follows. The op placement IS
    `simulate_zb(...).op_rounds` (split form) — what executes is what
    the simulator proved; this function only adds the message/stash slot
    bookkeeping."""
    import numpy as np

    n_mu = num_micro_batches
    rep = simulate_zb(n_mu, pp)
    starts = rep.op_rounds
    rounds = rep.makespan

    f_round = {(l, m): r for (k, l, m), r in starts.items() if k == "F"}
    b_round = {(l, m): r for (k, l, m), r in starts.items() if k == "B"}
    w_round = {(l, m): r for (k, l, m), r in starts.items() if k == "W"}

    act_msgs = [[] for _ in range(pp)]   # consumer-device intervals
    grad_msgs = [[] for _ in range(pp)]
    resb_items = [[] for _ in range(pp)]
    resw_items = [[] for _ in range(pp)]
    tap_items = [[] for _ in range(pp)]
    for (l, m), r_p in f_round.items():
        if l < pp - 1:
            act_msgs[l + 1].append(((l + 1, m), r_p, f_round[(l + 1, m)]))
        resb_items[l].append(((l, m), r_p, b_round[(l, m)]))
        resw_items[l].append(((l, m), r_p, w_round[(l, m)]))
    for (l, m), r_p in b_round.items():
        if l > 0:
            grad_msgs[l - 1].append(((l - 1, m), r_p,
                                     b_round[(l - 1, m)]))
        tap_items[l].append(((l, m), r_p, w_round[(l, m)]))

    assigns = []
    counts = []
    for items in (act_msgs, grad_msgs, resb_items, resw_items,
                  tap_items):
        assign, n = {}, 0
        for d in range(pp):
            a, na = _color_intervals(items[d])
            assign.update(a)
            n = max(n, na)
        assigns.append(assign)
        counts.append(n)
    act_a, grad_a, resb_a, resw_a, tap_a = assigns
    n_act, n_grad, n_resb, n_resw, n_tap = counts

    op_t = np.zeros((rounds, pp), np.int32)
    mu_t = np.zeros((rounds, pp), np.int32)
    act_r = np.full((rounds, pp), n_act, np.int32)
    act_w = np.full((rounds, pp), n_act, np.int32)
    grad_r = np.full((rounds, pp), n_grad, np.int32)
    grad_w = np.full((rounds, pp), n_grad, np.int32)
    resb_w = np.full((rounds, pp), n_resb, np.int32)
    resb_r = np.full((rounds, pp), n_resb, np.int32)
    resw_w = np.full((rounds, pp), n_resw, np.int32)
    resw_r = np.full((rounds, pp), n_resw, np.int32)
    resw_rb = np.full((rounds, pp), n_resw, np.int32)
    tap_w = np.full((rounds, pp), n_tap, np.int32)
    tap_r = np.full((rounds, pp), n_tap, np.int32)
    code = {"F": 1, "B": 2, "W": 3}
    for (kind, l, m), r in starts.items():
        assert op_t[r, l] == 0, (
            f"device {l} double-booked at round {r}")
        op_t[r, l] = code[kind]
        mu_t[r, l] = m
        if kind == "F":
            if l > 0:
                act_r[r, l] = act_a[(l, m)]
            resb_w[r, l] = resb_a[(l, m)]
            resw_w[r, l] = resw_a[(l, m)]
            if l < pp - 1:
                act_w[r, l + 1] = act_a[(l + 1, m)]
        elif kind == "B":
            if l < pp - 1:
                grad_r[r, l] = grad_a[(l, m)]
            resb_r[r, l] = resb_a[(l, m)]
            resw_rb[r, l] = resw_a[(l, m)]
            tap_w[r, l] = tap_a[(l, m)]
            if l > 0:
                grad_w[r, l - 1] = grad_a[(l - 1, m)]
        else:
            resw_r[r, l] = resw_a[(l, m)]
            tap_r[r, l] = tap_a[(l, m)]

    return ZBTables(
        n_rounds=rounds, n_act_slots=n_act, n_grad_slots=n_grad,
        n_resb_slots=n_resb, n_resw_slots=n_resw, n_tap_slots=n_tap,
        op=op_t, mu=mu_t, act_read=act_r, act_write=act_w,
        grad_read=grad_r, grad_write=grad_w, resb_write=resb_w,
        resb_read=resb_r, resw_write=resw_w, resw_read=resw_r,
        resw_read_b=resw_rb, tap_write=tap_w, tap_read=tap_r)
