"""Schedule verification — the `happens_before` upgrade the reference's
own tests ask for.

The reference's schedule tests check instruction *presence and coarse
ordering* and say so honestly: "these tests are weak [...] a
happens_before predicate would be the upgrade"
(`/root/reference/tests/test_schedules.py:4-10`). This module IS that
upgrade: it executes all stages' instruction streams against channel
semantics (activations flow right, cotangents flow left, FIFO per edge)
and proves, for any (num_stages, num_micro_batches):

- **deadlock-freedom**: every Recv is eventually satisfiable — the
  schedule can run to completion under blocking channels;
- **data correctness**: each Forward consumes the activation of ITS
  microbatch (channel tags must match — a schedule that reorders sends
  is caught, not just one that forgets them); each Backward consumes the
  matching cotangent and a stashed forward that exists and is used
  exactly once;
- **reduction placement**: exactly one BackwardGradAllReduce per stage
  per batch, as that stage's final backward, after ZeroGrad and before
  OptimizerStep (the reference's interleaved-DDP contract,
  `pipe.py:302-327`);
- **memory bounds**: the simulator measures each stage's PEAK activation
  stash, so 1F1B's min(num_stages - stage_id, n_mu) claim is checked,
  not asserted;
- **makespan**: unit-cost compute rounds give each schedule's bubble — a
  quantitative schedule-research metric (Naive >> GPipe ≈ 1F1B).

Pure Python over pure-data schedules: no devices, no arrays — the same
zero-process testability the schedule layer was designed for.
"""

from __future__ import annotations

from dataclasses import dataclass, field

from shallowspeed_tpu.parallel.instructions import (
    BackwardGradAcc,
    BackwardGradAllReduce,
    Forward,
    LoadMuBatchInput,
    LoadMuBatchTarget,
    OptimizerStep,
    RecvActivations,
    RecvOutputGrad,
    SendActivations,
    SendInputGrad,
    ZeroGrad,
)

_COMPUTE = (Forward, BackwardGradAcc, BackwardGradAllReduce)


class ScheduleError(AssertionError):
    """A schedule violated channel semantics or a pipeline invariant."""


@dataclass
class SimReport:
    """What the simulator proved/measured for one schedule instance."""

    makespan: int                      # unit-cost compute rounds to drain
    peak_stash: list                   # per-stage peak in-flight forwards
    fwd_rounds: dict = field(default_factory=dict)   # (stage, mu) -> round
    bwd_rounds: dict = field(default_factory=dict)


def _flatten(schedule) -> list:
    return [cmd for step in schedule.steps() for cmd in step]


def simulate(schedule_cls, num_micro_batches: int, num_stages: int,
             training: bool = True) -> SimReport:
    """Run every stage's instruction stream against FIFO channel
    semantics; raise ScheduleError on any violation (see module
    docstring for the list). `training=False` relaxes the
    backward/reduction invariants (inference schedules)."""
    n_mu = num_micro_batches
    progs = [_flatten(schedule_cls(n_mu, num_stages, s))
             for s in range(num_stages)]
    pc = [0] * num_stages
    # channels keyed by receiving stage; values are microbatch tags
    act_ch = [[] for _ in range(num_stages)]    # from stage s-1
    grad_ch = [[] for _ in range(num_stages)]   # from stage s+1
    bufs = [{} for _ in range(num_stages)]      # buffer_id -> mu tag
    stash = [set() for _ in range(num_stages)]  # forwards awaiting bwd
    peak = [0] * num_stages
    fwd_done = [set() for _ in range(num_stages)]
    bwd_done = [set() for _ in range(num_stages)]
    allreduce_seen = [False] * num_stages
    zerograd_seen = [False] * num_stages
    opt_seen = [False] * num_stages
    report = SimReport(0, peak)

    def err(s, msg):
        raise ScheduleError(
            f"stage {s}/{num_stages}, n_mu={n_mu}, "
            f"pc={pc[s]} ({progs[s][pc[s]] if pc[s] < len(progs[s]) else 'end'}): {msg}")

    def runnable(s):
        if pc[s] >= len(progs[s]):
            return False
        cmd = progs[s][pc[s]]
        if isinstance(cmd, RecvActivations):
            return bool(act_ch[s])
        if isinstance(cmd, RecvOutputGrad):
            return bool(grad_ch[s])
        return True

    def execute(s):
        cmd = progs[s][pc[s]]
        if isinstance(cmd, ZeroGrad):
            if fwd_done[s] or bwd_done[s]:
                err(s, "ZeroGrad after compute began")
            zerograd_seen[s] = True
        elif isinstance(cmd, LoadMuBatchInput):
            if s != 0:
                err(s, "LoadMuBatchInput on a non-first stage")
            bufs[s][cmd.buffer_id] = cmd.mubatch_id
        elif isinstance(cmd, LoadMuBatchTarget):
            if s != num_stages - 1:
                err(s, "LoadMuBatchTarget on a non-last stage")
            bufs[s][cmd.buffer_id] = cmd.mubatch_id
        elif isinstance(cmd, RecvActivations):
            bufs[s][cmd.buffer_id] = act_ch[s].pop(0)
        elif isinstance(cmd, RecvOutputGrad):
            bufs[s][cmd.buffer_id] = grad_ch[s].pop(0)
        elif isinstance(cmd, Forward):
            got = bufs[s].get(cmd.buffer_id)
            if got != cmd.mubatch_id:
                err(s, f"Forward(mu={cmd.mubatch_id}) consumed the "
                       f"activation of mu={got}")
            if cmd.mubatch_id in fwd_done[s]:
                err(s, f"second Forward of mu={cmd.mubatch_id}")
            fwd_done[s].add(cmd.mubatch_id)
            if training:
                stash[s].add(cmd.mubatch_id)
                peak[s] = max(peak[s], len(stash[s]))
            report.fwd_rounds[(s, cmd.mubatch_id)] = report.makespan
        elif isinstance(cmd, SendActivations):
            if s == num_stages - 1:
                err(s, "SendActivations off the pipeline's last stage")
            act_ch[s + 1].append(bufs[s].get(cmd.buffer_id))
        elif isinstance(cmd, (BackwardGradAcc, BackwardGradAllReduce)):
            got = bufs[s].get(cmd.buffer_id)
            if got != cmd.mubatch_id:
                err(s, f"Backward(mu={cmd.mubatch_id}) consumed the "
                       f"cotangent of mu={got}")
            if cmd.mubatch_id not in stash[s]:
                err(s, f"Backward(mu={cmd.mubatch_id}) without a stashed "
                       f"forward (missing, or consumed twice)")
            stash[s].remove(cmd.mubatch_id)
            bwd_done[s].add(cmd.mubatch_id)
            report.bwd_rounds[(s, cmd.mubatch_id)] = report.makespan
            if isinstance(cmd, BackwardGradAllReduce):
                if allreduce_seen[s]:
                    err(s, "second BackwardGradAllReduce in one batch")
                allreduce_seen[s] = True
            elif allreduce_seen[s]:
                err(s, "BackwardGradAcc AFTER the all-reduce backward "
                       "(its gradient would miss the DP reduction)")
        elif isinstance(cmd, SendInputGrad):
            if s == 0:
                err(s, "SendInputGrad off the pipeline's first stage")
            grad_ch[s - 1].append(bufs[s].get(cmd.buffer_id))
        elif isinstance(cmd, OptimizerStep):
            if len(bwd_done[s]) != n_mu:
                err(s, f"OptimizerStep after only {len(bwd_done[s])}/"
                       f"{n_mu} backwards")
            if not allreduce_seen[s]:
                err(s, "OptimizerStep without a DP all-reduce backward")
            opt_seen[s] = True
        else:
            err(s, f"unknown instruction {cmd}")
        pc[s] += 1

    # round-based: every stage executes zero-cost instructions freely and
    # at most ONE compute instruction per round (unit-cost model)
    while any(pc[s] < len(progs[s]) for s in range(num_stages)):
        progressed = False
        for s in range(num_stages):
            computed = False
            while runnable(s) and not computed:
                computed = isinstance(progs[s][pc[s]], _COMPUTE)
                execute(s)
                progressed = True
        if not progressed:
            stuck = [(s, str(progs[s][pc[s]]))
                     for s in range(num_stages) if pc[s] < len(progs[s])]
            raise ScheduleError(
                f"deadlock with n_mu={n_mu}, stages={num_stages}: every "
                f"remaining stage is blocked on a Recv: {stuck}")
        report.makespan += 1

    for s in range(num_stages):
        if act_ch[s] or grad_ch[s]:
            err(s, f"undelivered messages at drain: act={act_ch[s]} "
                   f"grad={grad_ch[s]}")
        if fwd_done[s] != set(range(n_mu)):
            err(s, f"forwards run: {sorted(fwd_done[s])} != all {n_mu}")
        if training:
            if bwd_done[s] != set(range(n_mu)):
                err(s, f"backwards run: {sorted(bwd_done[s])}")
            if not (zerograd_seen[s] and opt_seen[s]):
                err(s, "missing ZeroGrad/OptimizerStep bracket")
    # cross-stage happens-before: stage s+1's forward of mu cannot precede
    # stage s's (tags already prove data flow; this proves the timing)
    for (s, mu), r in report.fwd_rounds.items():
        if s + 1 < num_stages:
            nxt = report.fwd_rounds[(s + 1, mu)]
            if nxt < r:
                raise ScheduleError(
                    f"FWD({s + 1}, {mu}) at round {nxt} precedes "
                    f"FWD({s}, {mu}) at round {r}")
    return report


# public-API alias (`shallowspeed_tpu.simulate_schedule`): the package
# namespace needs a name that says what is simulated
simulate_schedule = simulate


# ------------------------------------------- interleaved 1F1B (virtual)


@dataclass
class InterleavedReport:
    """Device-level simulation result for interleaved 1F1B."""

    makespan: int            # chunk-unit rounds (one chunk = 1 unit)
    plain_makespan: int      # plain 1F1B at depth pp, scaled to chunk units
    peak_stash: list         # per-DEVICE peak in-flight forward stashes
    logical: SimReport       # full channel-semantics proof at depth pp*vpp


def simulate_interleaved(num_micro_batches: int, pp: int,
                         vpp: int) -> InterleavedReport:
    """Interleaved (virtual-stage) 1F1B — Megatron-style: device d hosts
    logical stages {d, d+pp, ..., d+(vpp-1)pp}, each running the plain
    1F1B instruction stream at logical depth pp*vpp.

    Two-level proof:
    - the LOGICAL pipeline is verified with full channel semantics by
      `simulate` (deadlock-freedom, tag-matched dataflow, per-logical-
      stage stash bound) — interleaving changes device placement, not
      the streams;
    - this function then list-schedules those verified streams under
      DEVICE contention (each device executes at most one chunk-compute
      per round; drain-first priority: a ready backward beats a ready
      forward, matching 1F1B's memory discipline) and measures the real
      makespan in chunk units plus each device's aggregate stash peak.

    The interleaving win: plain 1F1B's bubble is (pp-1) FULL-stage units
    while the virtual schedule's is (pp*vpp-1) CHUNK units = (pp-1) + a
    vpp-fraction — `makespan < plain_makespan` for n_mu >= pp (asserted
    in tests, reported here).
    """
    from shallowspeed_tpu.parallel.schedules import PipeDreamSchedule

    n_mu = num_micro_batches
    depth = pp * vpp
    logical = simulate(PipeDreamSchedule, n_mu, depth)
    plain = simulate(PipeDreamSchedule, n_mu, pp)

    # per-logical-stage op streams in 1F1B order: ("F"|"B", mu)
    def stream(stage):
        ops = []
        warm = min(depth - stage - 1, n_mu)
        ops += [("F", m) for m in range(warm)]
        for i in range(n_mu - warm):
            ops += [("F", warm + i), ("B", i)]
        ops += [("B", m) for m in range(n_mu - warm, n_mu)]
        return ops

    streams = {ls: stream(ls) for ls in range(depth)}
    pos = {ls: 0 for ls in range(depth)}
    f_done = {}                      # (ls, mu) -> completion round
    b_done = {}
    stash = [0] * pp                 # per-device in-flight forwards
    peak = [0] * pp
    rounds = 0
    total_ops = sum(len(s) for s in streams.values())
    done_ops = 0

    def ready(ls, rnd):
        if pos[ls] >= len(streams[ls]):
            return False
        op, mu = streams[ls][pos[ls]]
        if op == "F":
            return ls == 0 or f_done.get((ls - 1, mu), rnd) < rnd
        return (f_done.get((ls, mu), rnd) < rnd
                and (ls == depth - 1
                     or b_done.get((ls + 1, mu), rnd) < rnd))

    while done_ops < total_ops:
        progressed = False
        for d in range(pp):
            cands = [ls for ls in range(d, depth, pp) if ready(ls, rounds)]
            if not cands:
                continue
            # drain-first: backwards beat forwards; deeper chunks first
            def prio(ls):
                op, mu = streams[ls][pos[ls]]
                return (0 if op == "B" else 1, -ls, mu)

            ls = min(cands, key=prio)
            op, mu = streams[ls][pos[ls]]
            if op == "F":
                f_done[(ls, mu)] = rounds
                stash[d] += 1
                peak[d] = max(peak[d], stash[d])
            else:
                b_done[(ls, mu)] = rounds
                stash[d] -= 1
            pos[ls] += 1
            done_ops += 1
            progressed = True
        rounds += 1
        if not progressed and done_ops < total_ops:
            raise ScheduleError(
                f"interleaved schedule wedged at round {rounds} "
                f"(pp={pp}, vpp={vpp}, n_mu={n_mu})")

    return InterleavedReport(
        makespan=rounds,
        plain_makespan=plain.makespan * vpp,
        peak_stash=peak,
        logical=logical,
    )
