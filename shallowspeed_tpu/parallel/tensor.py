"""Tensor parallelism — Megatron-style sharded transformer via GSPMD.

The reference has no tensor parallelism (SURVEY §2: "no column/row-sharded
matmul anywhere"); this engine adds it the most TPU-native way there is: no
hand-written collectives at all. Following the scaling-book recipe — pick a
mesh, annotate the shardings, let XLA insert the collectives — the engine
places each transformer block's parameters Megatron-style on a (dp, tp)
mesh:

- `qkv` and `up` projections: column-sharded, `P(None, 'tp')` — each device
  owns `n_heads/tp` heads and `4*d/tp` MLP neurons; the head-dim reshape
  keeps attention fully local to a device.
- `proj` and `down` projections: row-sharded, `P('tp', None)` — XLA emits
  the single all-reduce per block that Megatron places by hand.
- embeddings / layernorms: replicated; `head`: column-sharded over vocab
  (the final log-softmax's cross-vocab reductions become tp collectives).
- batch over 'dp': the gradient all-reduce over 'dp' is likewise inferred
  by GSPMD from the sharding propagation through `jax.value_and_grad`.

The model code (`models/transformer.py`) is untouched — tensor parallelism
here is purely a *placement* decision, which is exactly the property that
makes the GSPMD formulation composable and compiler-optimizable (collective
scheduling, fusion with producers/consumers) in ways hand-rolled NCCL-style
code is not. The training loop / checkpoint plumbing is shared with the
other GSPMD engines (`parallel/gspmd.py`).
"""

from __future__ import annotations

from jax.sharding import Mesh, PartitionSpec as P

from shallowspeed_tpu.models import transformer as T
from shallowspeed_tpu.parallel.gspmd import GSPMDEngine


def param_specs(cfg: T.TransformerConfig) -> dict:
    """PartitionSpec pytree matching `transformer.init`'s structure."""
    col = {"W": P(None, "tp"), "b": P("tp")}
    row = {"W": P("tp", None), "b": P()}
    ln = {"g": P(), "b": P()}
    # GQA splits the attention projection: q and kv both column-sharded
    # (whole head groups per shard; needs kv_heads % tp == 0 too)
    attn_proj = {"q": col, "kv": col} if cfg.gqa else {"qkv": col}
    block = {"ln1": ln, **attn_proj, "proj": row,
             "ln2": ln, "up": col, "down": row}
    if cfg.ffn == "swiglu" and cfg.n_experts == 0:
        # SwiGLU's gate is column-parallel like up: the elementwise
        # silu(gate) * up then stays local to each tp shard
        block = {**block, "gate": col}
    out = {
        "tok_emb": P(),
        "pos_emb": P(),
        "blocks": [block for _ in range(cfg.n_layers)],
        "ln_f": ln,
    }
    if not cfg.tie_embeddings:
        out["head"] = col
    return out


class TensorParallelEngine(GSPMDEngine):
    """Data x tensor parallel trainer for the transformer LM family."""

    def validate(self, cfg: T.TransformerConfig, mesh: Mesh) -> None:
        assert mesh.axis_names == ("dp", "tp")
        self.tp = mesh.devices.shape[1]
        assert cfg.n_heads % self.tp == 0, (
            f"n_heads={cfg.n_heads} must be divisible by tp={self.tp}")
        assert cfg.kv_heads % self.tp == 0, (
            f"n_kv_heads={cfg.kv_heads} must be divisible by tp={self.tp}")
        assert (4 * cfg.d_model) % self.tp == 0
        assert cfg.n_experts == 0, (
            "TensorParallelEngine shards the dense FFN; use "
            "ExpertParallelEngine for MoE configs")

    def param_specs(self, cfg: T.TransformerConfig) -> dict:
        return param_specs(cfg)
