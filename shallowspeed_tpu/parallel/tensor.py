"""Tensor parallelism — Megatron-style sharded transformer via GSPMD.

The reference has no tensor parallelism (SURVEY §2: "no column/row-sharded
matmul anywhere"); this engine adds it the most TPU-native way there is: no
hand-written collectives at all. Following the scaling-book recipe — pick a
mesh, annotate the shardings, let XLA insert the collectives — the engine
places each transformer block's parameters Megatron-style on a (dp, tp)
mesh:

- `qkv` and `up` projections: column-sharded, `P(None, 'tp')` — each device
  owns `n_heads/tp` heads and `4*d/tp` MLP neurons; the head-dim reshape
  keeps attention fully local to a device.
- `proj` and `down` projections: row-sharded, `P('tp', None)` — XLA emits
  the single all-reduce per block that Megatron places by hand.
- embeddings / layernorms: replicated; `head`: column-sharded over vocab
  (the final log-softmax's cross-vocab reductions become tp collectives).
- batch over 'dp': the gradient all-reduce over 'dp' is likewise inferred
  by GSPMD from the sharding propagation through `jax.value_and_grad`.

The model code (`models/transformer.py`) is untouched — tensor parallelism
here is purely a *placement* decision, which is exactly the property that
makes the GSPMD formulation composable and compiler-optimizable (collective
scheduling, fusion with producers/consumers) in ways hand-rolled NCCL-style
code is not.
"""

from __future__ import annotations

from functools import partial

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from shallowspeed_tpu.models import transformer as T

tree_map = jax.tree_util.tree_map


def param_specs(cfg: T.TransformerConfig) -> dict:
    """PartitionSpec pytree matching `transformer.init`'s structure."""
    col = {"W": P(None, "tp"), "b": P("tp")}
    row = {"W": P("tp", None), "b": P()}
    ln = {"g": P(), "b": P()}
    block = {"ln1": ln, "qkv": col, "proj": row,
             "ln2": ln, "up": col, "down": row}
    return {
        "tok_emb": P(),
        "pos_emb": P(),
        "blocks": [block for _ in range(cfg.n_layers)],
        "ln_f": ln,
        "head": col,
    }


class TensorParallelEngine:
    """Data x tensor parallel trainer for the transformer LM family."""

    def __init__(self, cfg: T.TransformerConfig, optimizer, mesh: Mesh,
                 seed: int = 0):
        assert mesh.axis_names == ("dp", "tp")
        self.cfg = cfg
        self.mesh = mesh
        self.dp, self.tp = mesh.devices.shape
        assert cfg.n_heads % self.tp == 0, (
            f"n_heads={cfg.n_heads} must be divisible by tp={self.tp}")
        assert (4 * cfg.d_model) % self.tp == 0
        self.optimizer = optimizer

        self.shardings = tree_map(
            lambda s: NamedSharding(mesh, s), param_specs(cfg),
            is_leaf=lambda x: isinstance(x, P))
        self.rep = NamedSharding(mesh, P())
        self.batch = NamedSharding(mesh, P("dp", None))

        self.params = jax.device_put(T.init(cfg, seed), self.shardings)
        # zeros_like preserves sharding, so optimizer moments inherit the
        # Megatron placement with no extra spec bookkeeping; leaves created
        # fresh (e.g. Adam's step counter) get pinned replicated.
        self.opt_state = tree_map(self._mesh_or_replicated,
                                  optimizer.init(self.params))

        opt = optimizer

        @partial(jax.jit, donate_argnums=(0, 1))
        def _step(params, opt_state, tokens, targets):
            loss, grads = jax.value_and_grad(
                lambda p: T.loss(p, tokens, targets, cfg))(params)
            params, opt_state = opt.step(params, grads, opt_state)
            return params, opt_state, loss

        self._step_fn = _step
        self._eval_fn = jax.jit(
            lambda p, tok, tgt: T.loss(p, tok, tgt, cfg))
        self._logits_fn = jax.jit(
            lambda p, tok: T.forward(p, tok, cfg))

    def _mesh_or_replicated(self, leaf):
        """Keep a leaf's mesh placement if it has one; replicate otherwise."""
        if isinstance(getattr(leaf, "sharding", None), NamedSharding):
            return leaf
        return jax.device_put(leaf, self.rep)

    def _place(self, arr: np.ndarray):
        assert arr.shape[0] % self.dp == 0, (arr.shape, self.dp)
        assert arr.shape[1] <= self.cfg.max_seq
        return jax.device_put(arr, self.batch)

    def train_batch(self, tokens: np.ndarray, targets: np.ndarray) -> float:
        self.params, self.opt_state, loss = self._step_fn(
            self.params, self.opt_state,
            self._place(tokens), self._place(targets))
        return float(loss)

    def eval_loss(self, tokens: np.ndarray, targets: np.ndarray) -> float:
        return float(self._eval_fn(
            self.params, self._place(tokens), self._place(targets)))

    def logits(self, tokens: np.ndarray) -> jax.Array:
        return self._logits_fn(self.params, self._place(tokens))

    # -------------------------------------------- checkpoint interface

    def get_canonical_params(self):
        return self.params

    def set_canonical_params(self, params):
        self.params = jax.device_put(
            jax.device_get(params), self.shardings)

    def set_opt_state(self, state):
        # re-place moments onto the Megatron shardings (state trees mirror
        # params for SGD-momentum / Adam's m and v; scalars go replicated);
        # the live opt_state is the placement template — same structure,
        # no transient duplicate allocation.
        def place(leaf, like):
            sh = getattr(like, "sharding", None)
            sh = sh if isinstance(sh, NamedSharding) else self.rep
            return jax.device_put(np.asarray(leaf), sh)

        self.opt_state = tree_map(place, state, self.opt_state)
