"""The pipeline VM executor — L4.

Capability parity with the reference `Worker` (`/root/reference/shallowspeed/
pipe.py:330-466`): allocates input/output comm buffers per schedule, interprets
the instruction stream through a class→method dispatch table
(`pipe.py:420-432`), and runs Forward/Backward/Zero/Step against the model.

Re-designed for single-controller JAX:

- The reference runs one `Worker` per MPI process; here ONE
  `PipelineExecutor` drives every stage of the pipeline from one Python
  process. Each stage gets a `StageRuntime` pinned to one *column* of the
  (dp, pp) mesh; the executor advances all stages' instruction streams with a
  make-progress loop over FIFO channels. JAX dispatch is asynchronous, so
  compute for different stages/devices overlaps in wall-clock even though
  dispatch is sequential — the single-controller analogue of the reference's
  concurrent ranks.
- `Send`/`Recv` (`pipe.py:367-381`, blocking MPI) become `jax.device_put`
  of the buffer onto the consumer stage's sharding — an async ICI transfer.
- DP is folded *into* each stage executable as SPMD: batches are sharded
  over the 'dp' axis of the stage's submesh, `BackwardGradAcc` keeps
  per-replica partial gradient sums exactly like the reference's per-rank
  `param.grad +=` (`layers.py:135-136`), and `BackwardGradAllReduce` performs
  one bucketed `lax.psum` of the whole accumulated pytree over 'dp'
  (replacing the per-parameter `Iallreduce`+`Waitall` choreography,
  `pipe.py:302-327`; the bucketing is the improvement the reference's own
  docstring points at, `pipe.py:309-310`).
- Activation stashes live in a per-stage dict keyed by mubatch_id — the
  executor-level equivalent of the reference's `_cache[f"input_{mubatch_id}"]`
  (`layers.py:70,117`), sized by the schedule (GPipe: n_mu; 1F1B: pipeline
  depth).
"""

from __future__ import annotations

from collections import deque
from functools import partial
from typing import Sequence

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

# version-compat shard_map (utils.py): VMA jax as-is; pre-VMA jax
# with the legacy replication rewriter disabled
from shallowspeed_tpu.utils import shard_map

from shallowspeed_tpu.models.mlp import MLPStage
from shallowspeed_tpu.parallel.instructions import (
    BackwardGradAcc,
    BackwardGradAllReduce,
    Forward,
    LoadMuBatchInput,
    LoadMuBatchTarget,
    OptimizerStep,
    RecvActivations,
    RecvOutputGrad,
    SendActivations,
    SendInputGrad,
    ZeroGrad,
)

tree_map = jax.tree_util.tree_map


class StageRuntime:
    """Device state + jitted executables for one pipeline stage.

    Owns: params (replicated over the stage's dp-submesh), the gradient
    accumulator (leading dp axis, sharded), optimizer state, activation
    stashes, and the comm buffers (`pipe.py:336-353,446-454`).
    """

    def __init__(self, stage: MLPStage, devices: np.ndarray, optimizer,
                 health: str = "off"):
        from shallowspeed_tpu.telemetry.health import MODES

        assert health in MODES, health
        self.health = health
        self.last_pack = None  # this STAGE's local health pack
        self._nf_batches = None  # device-side cumulative: batches with
        #                          nonfinite grads ON THIS STAGE
        self.stage = stage
        self.submesh = Mesh(np.asarray(devices).reshape(-1), axis_names=("dp",))
        self.dp = self.submesh.devices.size
        self.optimizer = optimizer

        self.rep = NamedSharding(self.submesh, P())        # replicated
        self.row = NamedSharding(self.submesh, P("dp"))    # batch-sharded

        self.params = jax.device_put(stage.init(), self.rep)
        self.opt_state = (jax.device_put(optimizer.init(self.params), self.rep)
                          if optimizer is not None else None)
        self.grad_acc = None     # (dp, ...) pytree, sharded over 'dp'
        self.reduced_grads = None  # replicated pytree after AllReduce
        self.stash: dict[int, object] = {}
        self.input_buffers: list = []
        self.output_buffers: list = []

        mesh, rt = self.submesh, self

        @partial(jax.jit)
        @partial(shard_map, mesh=mesh, in_specs=(P(), P("dp")),
                 out_specs=(P("dp"), P("dp")))
        def _fwd(params, x):
            out, stash = rt.stage.forward(params, x)
            return out, stash

        @partial(jax.jit)
        @partial(shard_map, mesh=mesh, in_specs=(P(), P("dp")),
                 out_specs=P("dp"))
        def _infer(params, x):
            return rt.stage.infer(params, x)

        @partial(jax.jit)
        @partial(shard_map, mesh=mesh,
                 in_specs=(P(), P("dp"), P("dp"), P("dp")),
                 out_specs=(P("dp"), P("dp")))
        def _bwd_acc(params, stash, dout, acc):
            dx, grads = rt.stage.backward(params, stash, dout)
            new_acc = tree_map(lambda a, g: a + g[None], acc, grads)
            return dx, new_acc

        @partial(jax.jit)
        @partial(shard_map, mesh=mesh, in_specs=(P(),),
                 out_specs=P("dp"))
        def _zeros_acc(params):
            # the accumulator must be born with the SAME sharding the
            # steady-state path produces (a shard_map output under
            # out_specs P('dp')): a plain device_put(zeros, row) carries
            # a differently-normalized sharding in the jit cache key, so
            # the second BackwardGradAcc of every batch silently
            # recompiled each stage's _bwd_acc — caught by telemetry's
            # recompile counter (PR 2), invisible before it
            return tree_map(
                lambda p: jnp.zeros((1,) + p.shape, p.dtype), params)

        health_mode = health
        ar_out = ((P("dp"), P()) if health == "off"
                  else (P("dp"), P(), P()))

        @partial(jax.jit)
        @partial(shard_map, mesh=mesh,
                 in_specs=(P(), P("dp"), P("dp"), P("dp")),
                 out_specs=ar_out)
        def _bwd_allreduce(params, stash, dout, acc):
            dx, grads = rt.stage.backward(params, stash, dout)
            new_acc = tree_map(lambda a, g: a + g[None], acc, grads)
            # One bucketed all-reduce of the whole accumulated pytree over
            # the dp axis (vs per-param Iallreduce, `pipe.py:302-316`).
            total = tree_map(
                lambda a: jax.lax.psum(a, "dp")[0], new_acc)
            if health_mode == "off":
                return dx, total
            # this STAGE's local health pack, fused into the same
            # executable (no extra entrypoint); the executor merges the
            # per-stage packs over pp on the host (health.merge_packs)
            from shallowspeed_tpu.telemetry.health import grad_health

            return dx, total, grad_health(params, total)

        def _opt(params, grads, opt_state, ok=None):
            # Per-stage update outside shard_map: `grad_clip` here clips by
            # the *stage's* gradient norm (stages are independent programs
            # in this interpreted engine). The compiled SPMD engine
            # (`spmd_pipeline.py`) clips by the true cross-stage global
            # norm via clip_axes=("pp",). Under health="guard" the
            # executor passes the GLOBAL ok (all stages' sentinels
            # host-combined) so the whole pipeline skips in lockstep.
            from shallowspeed_tpu.telemetry.health import update_health

            from shallowspeed_tpu.telemetry.health import param_l2

            if health_mode == "guard":
                new_p, new_s = rt.optimizer.guarded_step(
                    params, grads, opt_state, ok)
                upd = update_health({"param_norm": param_l2(params)},
                                    params, new_p,
                                    skipped=1 - ok.astype("int32"))
                return new_p, new_s, upd
            new_p, new_s = rt.optimizer.step(params, grads, opt_state)
            if health_mode == "off":
                return new_p, new_s
            upd = update_health({"param_norm": param_l2(params)},
                                params, new_p)
            return new_p, new_s, upd

        self._fwd = _fwd
        self._infer = _infer
        self._bwd_acc = _bwd_acc
        self._bwd_allreduce = _bwd_allreduce
        self._zeros_acc = _zeros_acc
        self._opt = jax.jit(_opt) if optimizer is not None else None

    # ------------------------------------------------------------ state ops

    def zero_grad(self):
        """Fresh (dp, ...) zero accumulator (`pipe.py:411-412`), built
        through the compiled producer so its sharding matches the
        steady-state `_bwd_acc` output (see `_zeros_acc`)."""
        self.grad_acc = self._zeros_acc(self.params)
        self.reduced_grads = None

    def forward(self, x, mubatch_id: int, training: bool = True):
        if training:
            out, stash = self._fwd(self.params, x)
            self.stash[mubatch_id] = stash
            return out
        return self._infer(self.params, x)

    def backward(self, dout, mubatch_id: int, allreduce: bool):
        stash = self.stash.pop(mubatch_id)
        if allreduce:
            out = self._bwd_allreduce(self.params, stash, dout,
                                      self.grad_acc)
            dx, self.reduced_grads = out[0], out[1]
            if self.health != "off":
                self.last_pack = out[2]
                # cumulative, lazily on device (no sync): a transient
                # bad batch between snapshot fetches is still counted
                bad = (out[2]["nonfinite"] > 0).astype("int32")
                self._nf_batches = (bad if self._nf_batches is None
                                    else self._nf_batches + bad)
        else:
            dx, self.grad_acc = self._bwd_acc(self.params, stash, dout,
                                              self.grad_acc)
        return dx

    def optimizer_step(self, ok=None):
        assert self.reduced_grads is not None, \
            "OptimizerStep before BackwardGradAllReduce"
        if self.health == "guard":
            self.params, self.opt_state, upd = self._opt(
                self.params, self.reduced_grads, self.opt_state, ok)
            self.last_pack = {**(self.last_pack or {}), **upd}
        elif self.health != "off":
            self.params, self.opt_state, upd = self._opt(
                self.params, self.reduced_grads, self.opt_state)
            self.last_pack = {**(self.last_pack or {}), **upd}
        else:
            self.params, self.opt_state = self._opt(
                self.params, self.reduced_grads, self.opt_state)
        self.reduced_grads = None


class PipelineExecutor:
    """Single-controller interpreter for per-stage instruction streams.

    `execute(schedules, batch_id, datasets)` is the counterpart of the
    reference's `Worker.execute(sched, batch_id)` (`pipe.py:434-466`), run for
    all stages at once: per-stage program counters advance whenever not
    blocked on an empty channel, sends enqueue async device-to-device
    transfers, and the loop terminates when every stream is drained (the FIFO
    pairing that MPI message ordering provided, `pipe.py:367-381`).
    """

    def __init__(self, mesh: Mesh, stages: Sequence[MLPStage], optimizer,
                 health: str = "off"):
        assert mesh.axis_names == ("dp", "pp")
        self.mesh = mesh
        self.dp, self.pp = mesh.devices.shape
        assert len(stages) == self.pp
        self.health = health
        self.health_skipped = 0   # batches skipped under "guard"
        self._guard_ok = None     # this batch's host-combined sentinel
        self.runtimes = [
            StageRuntime(stage, mesh.devices[:, s], optimizer,
                         health=health)
            for s, stage in enumerate(stages)]
        self._infer_outputs: list = []
        # measured comm accounting (telemetry): device-to-device hop
        # bytes (pp) and per-device dp-psum payload bytes, cumulative
        self.comm_bytes: dict[str, int] = {}

    @property
    def last(self) -> StageRuntime:
        return self.runtimes[-1]

    # ------------------------------------------------------------- data

    def _stacked(self, datasets, batch_id, mubatch_id, target: bool):
        """(dp * mubs, dim) host batch assembled from the per-replica strided
        shards, placed sharded over the stage's dp axis."""
        parts = [
            (ds.load_micro_batch_target if target
             else ds.load_micro_batch_input)(batch_id, mubatch_id)
            for ds in datasets]
        return np.concatenate(parts, axis=0)

    # ------------------------------------------------------------ execute

    def execute(self, schedules, batch_id: int, datasets,
                training: bool = True):
        """Run one batch. `schedules`: one Schedule per stage. `datasets`:
        list of dp per-rank Dataset shards (reference loads one shard per DP
        rank, `train.py:113-119`)."""
        from shallowspeed_tpu.telemetry import tracer

        progs = [list(_flatten(s.steps())) for s in schedules]
        pcs = [0] * self.pp
        self._infer_outputs = []
        # channels keyed (src, dst) hold in-flight device arrays (FIFO)
        channels: dict[tuple[int, int], deque] = {}

        def chan(src, dst):
            return channels.setdefault((src, dst), deque())

        total = sum(len(p) for p in progs)
        done = 0
        with tracer().span("batch", batch=batch_id,
                           training=training) as sp:
            while done < total:
                progress = False
                for s in range(self.pp):
                    rt = self.runtimes[s]
                    while pcs[s] < len(progs[s]):
                        cmd = progs[s][pcs[s]]
                        if isinstance(cmd, RecvActivations) \
                                and not chan(s - 1, s):
                            break
                        if isinstance(cmd, RecvOutputGrad) \
                                and not chan(s + 1, s):
                            break
                        if isinstance(cmd, OptimizerStep) \
                                and self.health == "guard" \
                                and self._guard_ok is None \
                                and any(r.reduced_grads is None
                                        for r in self.runtimes):
                            # the guarded update needs every stage's
                            # nonfinite sentinel: block the FIRST step
                            # of the batch until all stages have
                            # reduced (the reductions never depend on
                            # a step, so this cannot deadlock); once
                            # the combined sentinel exists, later
                            # stages step freely
                            break
                        self._dispatch(cmd, rt, s, batch_id, datasets,
                                       chan, training)
                        pcs[s] += 1
                        done += 1
                        progress = True
                if not progress:
                    raise RuntimeError(f"pipeline deadlock at pcs={pcs}")
            sp.fence(*[rt.params[0]["b"] for rt in self.runtimes])

    def _dispatch(self, cmd, rt: StageRuntime, s: int, batch_id, datasets,
                  chan, training):
        from shallowspeed_tpu.telemetry import tracer

        tr = tracer()
        if isinstance(cmd, ZeroGrad):
            rt.zero_grad()
            self._guard_ok = None  # a fresh batch, a fresh sentinel
        elif isinstance(cmd, OptimizerStep):
            with tr.span("OptimizerStep", stage=s, batch=batch_id) as sp:
                ok = None
                if self.health == "guard":
                    if self._guard_ok is None:
                        # ONE host sync per batch: combine every
                        # stage's nonfinite sentinel into the global
                        # skip decision all stages share
                        nf = sum(int(jax.device_get(
                            r.last_pack["nonfinite"]))
                            for r in self.runtimes)
                        self._guard_ok = np.asarray(nf == 0)
                        if nf:
                            self.health_skipped += 1
                    ok = self._guard_ok
                rt.optimizer_step(ok)
                sp.fence(rt.params[0]["b"])
        elif isinstance(cmd, LoadMuBatchInput):
            data = self._stacked(datasets, batch_id, cmd.mubatch_id, False)
            rt.input_buffers[cmd.buffer_id] = jax.device_put(data, rt.row)
        elif isinstance(cmd, LoadMuBatchTarget):
            data = self._stacked(datasets, batch_id, cmd.mubatch_id, True)
            rt.output_buffers[cmd.buffer_id] = jax.device_put(data, rt.row)
        elif isinstance(cmd, Forward):
            # the compute instructions carry (stage, mu, batch) span
            # attribution: at the `spans` level this IS the executed
            # schedule trace telemetry.bubble.trace_bubble replays
            # against verify.py's makespan model
            with tr.span("Forward", stage=s, mu=cmd.mubatch_id,
                         batch=batch_id) as sp:
                out = rt.forward(rt.input_buffers[cmd.buffer_id],
                                 cmd.mubatch_id, training)
                rt.output_buffers[cmd.buffer_id] = out
                sp.fence(out)
            if not training and rt is self.last:
                self._infer_outputs.append(out)
        elif isinstance(cmd, BackwardGradAcc):
            with tr.span("BackwardGradAcc", stage=s, mu=cmd.mubatch_id,
                         batch=batch_id) as sp:
                dx = rt.backward(rt.output_buffers[cmd.buffer_id],
                                 cmd.mubatch_id, False)
                rt.input_buffers[cmd.buffer_id] = dx
                sp.fence(dx)
        elif isinstance(cmd, BackwardGradAllReduce):
            with tr.span("BackwardGradAllReduce", stage=s,
                         mu=cmd.mubatch_id, batch=batch_id) as sp:
                dx = rt.backward(rt.output_buffers[cmd.buffer_id],
                                 cmd.mubatch_id, True)
                rt.input_buffers[cmd.buffer_id] = dx
                sp.fence(dx)
            # one bucketed dp-psum of the whole grad pytree ran inside:
            # measured collective accounting (bytes entering the psum)
            self.comm_bytes["dp_psum"] = self.comm_bytes.get(
                "dp_psum", 0) + self._grad_bytes(rt)
        elif isinstance(cmd, SendActivations):
            nxt = self.runtimes[s + 1]
            buf = rt.output_buffers[cmd.buffer_id]
            self.comm_bytes["pp_p2p"] = self.comm_bytes.get(
                "pp_p2p", 0) + int(buf.nbytes)
            chan(s, s + 1).append(jax.device_put(buf, nxt.row))
        elif isinstance(cmd, RecvActivations):
            rt.input_buffers[cmd.buffer_id] = chan(s - 1, s).popleft()
        elif isinstance(cmd, SendInputGrad):
            prv = self.runtimes[s - 1]
            buf = rt.input_buffers[cmd.buffer_id]
            self.comm_bytes["pp_p2p"] = self.comm_bytes.get(
                "pp_p2p", 0) + int(buf.nbytes)
            chan(s, s - 1).append(jax.device_put(buf, prv.row))
        elif isinstance(cmd, RecvOutputGrad):
            rt.output_buffers[cmd.buffer_id] = chan(s + 1, s).popleft()
        else:
            raise TypeError(f"unknown instruction {cmd!r}")

    @staticmethod
    def _grad_bytes(rt: StageRuntime) -> int:
        """Per-device payload of the stage's bucketed dp-psum: the
        whole params-shaped grad pytree (each device holds one (1, ...)
        shard of the (dp, ...) accumulator)."""
        return sum(int(l.nbytes) for layer in rt.params
                   for l in layer.values())

    # ----------------------------------------------- telemetry surface

    def telemetry_entrypoints(self) -> list:
        """Per-stage compiled executables (args=None: the VM measures
        its traffic directly via `comm_bytes` instead of a jaxpr walk,
        but the recompile counter still reads these caches)."""
        out = []
        for s, rt in enumerate(self.runtimes):
            for name, fn in (("fwd", rt._fwd), ("bwd", rt._bwd_acc),
                             ("bwd_ar", rt._bwd_allreduce),
                             ("opt", rt._opt), ("infer", rt._infer)):
                if fn is not None:
                    out.append({"name": f"s{s}.{name}", "fn": fn,
                                "args": None})
        return out

    def telemetry_traffic(self) -> dict:
        """MEASURED cumulative comm bytes (pp hop transfers, dp psum
        payloads) — the interpreted engine's counterpart of the
        compiled engines' static jaxpr-walk accounting."""
        return dict(self.comm_bytes)

    def health_snapshot(self) -> dict | None:
        """The last batch's health pack: per-STAGE local packs (each
        stage is its own executable) fetched and merged over pp on the
        host (health.merge_packs — norms combine as sqrt-of-sum-of-
        squares since stages partition the params; groups get an
        `s<i>.` prefix). None before the first batch or health='off'."""
        from shallowspeed_tpu.telemetry.health import (fetch_pack,
                                                       merge_packs)

        import jax

        merged = merge_packs(
            [fetch_pack(rt.last_pack) for rt in self.runtimes])
        if merged is None:
            return None
        # cumulative counters: batches-with-nonfinite is the max over
        # the per-stage device counters (one backward's NaN reaches a
        # contiguous stage suffix, so the worst stage saw every bad
        # batch); guarded skips are counted exactly on the host (the
        # guard already syncs once per batch)
        nf = [int(jax.device_get(rt._nf_batches))
              for rt in self.runtimes if rt._nf_batches is not None]
        if nf:
            merged["nonfinite_steps_total"] = max(nf)
        if self.health == "guard":
            merged["skipped"] = 1 if (self._guard_ok is not None
                                      and not self._guard_ok) else 0
            merged["skipped_total"] = self.health_skipped
        return merged

    def allocate_buffers(self, num_buffers: int):
        """Reference allocates numpy comm buffers per schedule
        (`pipe.py:446-454`); JAX arrays are immutable so buffers here are
        just slots — allocation is slot-count bookkeeping."""
        for rt in self.runtimes:
            n = num_buffers // 2
            rt.input_buffers = [None] * n
            rt.output_buffers = [None] * n

    # --------------------------------------------------------- conveniences

    def train_batch(self, schedule_cls, n_mubatches: int, batch_id: int,
                    datasets):
        scheds = [schedule_cls(n_mubatches, self.pp, s) for s in range(self.pp)]
        self.allocate_buffers(max(s.num_buffers for s in scheds))
        self.execute(scheds, batch_id, datasets, training=True)

    def infer_batch(self, schedule_cls, n_mubatches: int, batch_id: int,
                    datasets):
        """Forward-only streaming; returns the last stage's outputs for ALL
        microbatches, concatenated in microbatch order (reference
        `compute_accuracy`, `train.py:31-43`, uses one microbatch)."""
        scheds = [schedule_cls(n_mubatches, self.pp, s) for s in range(self.pp)]
        self.allocate_buffers(max(s.num_buffers for s in scheds))
        self.execute(scheds, batch_id, datasets, training=False)
        outs = self._infer_outputs
        return outs[0] if len(outs) == 1 else jnp.concatenate(outs, axis=0)

    @property
    def params(self):
        return [rt.params for rt in self.runtimes]

    @property
    def opt_state(self):
        return [rt.opt_state for rt in self.runtimes]

    # -------------------------------------------------- checkpoint interface

    def get_canonical_params(self):
        """Concatenate per-stage layer lists into the whole-model flat list."""
        return [layer for rt in self.runtimes for layer in rt.params]

    def set_canonical_params(self, layers):
        i = 0
        for rt in self.runtimes:
            n = rt.stage.n_linears
            rt.params = jax.device_put(list(layers[i:i + n]), rt.rep)
            i += n
        assert i == len(layers), (i, len(layers))

    def set_opt_state(self, states):
        assert len(states) == len(self.runtimes), (
            f"{len(states)} per-stage states for {len(self.runtimes)} stages")
        for rt, st in zip(self.runtimes, states):
            rt.opt_state = jax.device_put(st, rt.rep)

    @property
    def optimizer(self):
        return self.runtimes[0].optimizer

    def canon_opt_export(self):
        """Merge the per-stage optimizer states into the canonical
        whole-model state (the pp=1 layout): every params-shaped moment
        tree is a per-stage layer list, so the canonical moment is their
        concatenation in stage order — the exact transform
        `get_canonical_params` applies to the params. Stage-invariant
        scalars (step counters) come from stage 0 (all stages step in
        lockstep). None when the optimizer's state is not params-shaped."""
        states = [jax.device_get(rt.opt_state) for rt in self.runtimes]
        try:
            per_stage = []
            for st in states:
                trees: list = []
                self.optimizer.map_state_trees(
                    st, lambda t: (trees.append(t), t)[1])
                per_stage.append(trees)
        except ValueError:
            return None
        k = len(per_stage[0])
        if any(len(t) != k for t in per_stage):
            return None
        if k == 0:  # stateless / counter-only: any stage's copy
            return states[0]
        merged = iter([
            [layer for stage in per_stage for layer in stage[i]]
            for i in range(k)])
        return self.optimizer.map_state_trees(
            states[0], lambda _t: next(merged))

    def canon_opt_import(self, canon):
        """Split a canonical whole-model state back into per-stage
        states (the inverse of `canon_opt_export`)."""
        try:
            out, lo = [], 0
            for rt in self.runtimes:
                hi = lo + rt.stage.n_linears
                out.append(self.optimizer.map_state_trees(
                    canon, lambda tree, lo=lo, hi=hi: list(tree[lo:hi])))
                lo = hi
            return out
        except ValueError:
            return None


def _flatten(steps_gen):
    for step in steps_gen:
        yield from step
