"""Multi-host distributed runtime: process init, ICI x DCN meshes, and
host-local data placement.

The reference scales across hosts with `mpirun` + MPI communicator splits
(`/root/reference/train.py:87-94`, noting `Split_type`/`TYPE_SOCKET` for
"physically distributed" runs, `train.py:90-91`). The TPU-native
counterpart is multi-controller JAX: one Python process per host, all
connected through the JAX distributed service; collectives ride ICI inside
a pod slice and DCN between slices, compiled into the XLA program — no
MPI/NCCL dependency.

Everything in this module degrades to a no-op / plain-JAX behavior in a
single-process run, so the same driver script works from one chip to a
multi-pod fleet:

- `initialize()`: `jax.distributed.initialize` with env-var autodetection,
  idempotent, no-op when single-process.
- `hybrid_mesh(...)`: an ICI x DCN-aware mesh. The slowest-varying
  (leftmost) axes land on DCN, per the scaling-book recipe: data
  parallelism (gradient all-reduce, one collective per step) tolerates
  DCN latency; model axes (tp/sp collectives on every layer) must stay
  on ICI inside a slice.
- `place_global(...)`: build a globally-sharded array from each process's
  host-local batch shard — the multi-host replacement for
  `jax.device_put(np_array, sharding)`, which only works when every
  process holds the full global array.
- `process_zero()` / `barrier()`: control-plane helpers (the reference's
  rank-0 guard and sync points).
"""

from __future__ import annotations

import functools
import os

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec


def initialize(coordinator_address: str | None = None,
               num_processes: int | None = None,
               process_id: int | None = None) -> bool:
    """Connect this process to the JAX distributed service.

    Call once per process, before first backend use. Arguments default to
    the standard env vars (`JAX_COORDINATOR_ADDRESS`, `JAX_NUM_PROCESSES`,
    `JAX_PROCESS_ID`). Strictly opt-in: without an explicit coordinator
    address (argument or env var) this is a no-op, even on hardware whose
    metadata advertises a pod — single-host TPU images often do (this one
    sets `TPU_WORKER_HOSTNAMES=localhost`), and an unwanted init attempt
    after backend startup is a hard error. Returns True if a multi-process
    runtime was set up, False for the single-process no-op or when already
    initialized (idempotent).
    """
    coordinator_address = (coordinator_address
                           or os.environ.get("JAX_COORDINATOR_ADDRESS"))
    if coordinator_address is None:
        return False  # single-process run
    try:
        jax.distributed.initialize(
            coordinator_address=coordinator_address,
            num_processes=(num_processes
                           if num_processes is not None
                           else _env_int("JAX_NUM_PROCESSES")),
            process_id=(process_id if process_id is not None
                        else _env_int("JAX_PROCESS_ID")))
        return True
    except RuntimeError as e:  # already initialized — idempotent
        # jax has used both wordings across versions: "already
        # initialized" and "initialize should only be called once"
        msg = str(e).lower()
        if "already initialized" in msg or "called once" in msg:
            return False
        raise


def _env_int(name: str) -> int | None:
    v = os.environ.get(name)
    return int(v) if v is not None else None


def process_zero() -> bool:
    """The reference's rank-0 guard (`utils.py:8-10`), multi-controller."""
    return jax.process_index() == 0


def barrier(tag: str = "barrier") -> None:
    """Block until every process reaches this point (no-op single-process).
    The control-plane sync the reference gets implicitly from MPI
    collectives (`utils.py:27-31`)."""
    if jax.process_count() > 1:
        from jax.experimental import multihost_utils

        multihost_utils.sync_global_devices(tag)


def all_ok(flag: bool) -> bool:
    """Collective AND of a per-process success bit; doubles as a barrier.

    Use wherever one process can fail while its peers would otherwise
    proceed trusting shared state (e.g. an async checkpoint write that
    only process 0 performs): every process learns the fleet-wide
    verdict at the same point, so failures raise TOGETHER instead of
    wedging the gang in the next collective. Single-process: returns
    `flag` unchanged."""
    if jax.process_count() == 1:
        return bool(flag)
    from jax.experimental import multihost_utils

    bits = multihost_utils.process_allgather(np.asarray(bool(flag)))
    return bool(np.all(bits))


def hybrid_mesh(axis_names: tuple[str, ...], axis_sizes: tuple[int, ...],
                *, dcn_axes: int = 1, devices=None) -> Mesh:
    """A mesh whose leftmost `dcn_axes` axes span slices over DCN and whose
    remaining axes stay inside a slice on ICI.

    Single-slice / single-host (or CPU-simulated) runs fall back to a plain
    row-major reshape — same axis names, same program, so drivers don't
    branch. Axis ORDER is the contract: put dp (and fsdp) leftmost, model
    axes (sp/tp/ep, pp) rightmost, because the leftmost axes get the
    slow links (one gradient collective per step) and the rightmost get
    ICI (collectives on every layer).
    """
    assert len(axis_names) == len(axis_sizes)
    if devices is None:
        devices = jax.devices()
    n = int(np.prod(axis_sizes))
    assert n <= len(devices), (
        f"mesh {dict(zip(axis_names, axis_sizes))} needs {n} devices, "
        f"have {len(devices)}")
    by_slice: dict[int, list] = {}
    for d in devices:
        by_slice.setdefault(getattr(d, "slice_index", 0), []).append(d)
    if len(by_slice) > 1:
        from jax.experimental import mesh_utils

        dcn = int(np.prod(axis_sizes[:dcn_axes]))
        per_slice = int(np.prod(axis_sizes[dcn_axes:]))
        if dcn != len(by_slice):
            raise ValueError(
                f"the leftmost {dcn_axes} (DCN) axes have product {dcn} "
                f"but the fleet has {len(by_slice)} slices; size the DCN "
                f"axes to the slice count (or pass a `devices` subset)")
        short = {s: len(v) for s, v in by_slice.items() if len(v) < per_slice}
        if short:
            raise ValueError(
                f"ICI axes need {per_slice} devices per slice; slices "
                f"{sorted(short)} have only {short}")
        picked = [d for s in sorted(by_slice)
                  for d in by_slice[s][:per_slice]]
        grid = mesh_utils.create_hybrid_device_mesh(
            mesh_shape=axis_sizes[dcn_axes:],
            dcn_mesh_shape=axis_sizes[:dcn_axes] + (1,) * (
                len(axis_sizes) - dcn_axes),
            devices=picked)
        return Mesh(grid.reshape(axis_sizes), axis_names)
    grid = np.array(devices[:n]).reshape(axis_sizes)
    return Mesh(grid, axis_names)


def place_global(arr: np.ndarray, sharding: NamedSharding,
                 local: bool = True) -> jax.Array:
    """Assemble a globally-sharded jax.Array across processes.

    Single-process: plain `device_put` (arr is the global array).
    Multi-process, `local=True` (default): `arr` is this host's shard of
    the global batch — e.g. with the global batch sharded over 'dp' and
    P processes, each process passes its B/P rows — and the pieces are
    stitched into one global array without any host ever holding the
    whole thing. This is how the reference's per-rank
    `Dataset.load(DP_rank, DP_size)` strided shards (`dataset.py:54-58`)
    map to single-controller-per-host JAX.

    Multi-process, `local=False`: every process holds the SAME full
    global array (deterministically built batches); each device pulls
    its slice via `make_array_from_callback`. Callers that replicate
    batch construction (the pipeline engine's microbatch splitter) MUST
    use this form — `make_array_from_process_local_data` would silently
    misread a full-global array as the process-local block whenever a
    sharded dimension spans processes.
    """
    if isinstance(arr, jax.Array) or jax.process_count() == 1:
        # already placed (no-op/reshard) or single-process global array
        return jax.device_put(arr, sharding)
    if not local:
        arr = np.asarray(arr)
        return jax.make_array_from_callback(
            arr.shape, sharding, lambda idx: arr[idx])
    return jax.make_array_from_process_local_data(sharding, arr)


def local_rows(arr: np.ndarray) -> np.ndarray:
    """This process's row-block of a globally-identical batch.

    Drivers build batches deterministically (seeded per step) so every
    process materializes the same global array; each keeps only its
    contiguous `B/P` rows to feed `place_global`. No-op single-process.
    Row-block (not strided) so the concatenation order
    `make_array_from_process_local_data` assumes matches row order.
    """
    p = jax.process_count()
    if p == 1:
        return arr
    assert arr.shape[0] % p == 0, (
        f"global batch of {arr.shape[0]} rows must divide over {p} "
        f"processes")
    rows = arr.shape[0] // p
    i = jax.process_index()
    return arr[i * rows:(i + 1) * rows]


def fetch_global(tree):
    """`jax.device_get` that also works on MULTI-CONTROLLER globally
    sharded pytrees (round 4 — the checkpoint path's fetch).

    Single-process: plain device_get. Multi-process: a leaf sharded
    over a mesh axis that spans processes is not fully addressable, so
    device_get would raise; replicate every jax.Array leaf first (jit
    identity with replicated out_shardings — XLA inserts the
    all-gathers, riding ICI/DCN) and read the now-local full copy.
    Collective: EVERY process must call this together (same order), the
    same way they issue training steps."""
    if jax.process_count() == 1:
        return jax.device_get(tree)

    def fetch(leaf):
        if not isinstance(leaf, jax.Array):
            return np.asarray(leaf)
        sh = getattr(leaf, "sharding", None)
        if getattr(leaf, "is_fully_addressable", True):
            return np.asarray(jax.device_get(leaf))
        rep = _replicator(NamedSharding(sh.mesh, PartitionSpec()))(leaf)
        return np.asarray(jax.device_get(rep))

    return jax.tree_util.tree_map(fetch, tree)


@functools.lru_cache(maxsize=64)
def _replicator(sharding: NamedSharding):
    """Cached jitted identity-with-replication: jit caches on function
    identity, so a fresh lambda per leaf would recompile the replicate
    program on every checkpoint save — one program per target sharding
    (per mesh) serves every leaf instead."""
    return jax.jit(lambda x: x, out_shardings=sharding)
