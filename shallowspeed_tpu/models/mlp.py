"""Stage-partitioned MLP as pure functions over parameter pytrees — the L2 layer.

Capability parity with the reference's Module system + MLP
(`/root/reference/shallowspeed/layers.py:17-270`), re-designed functionally for
XLA:

- Parameters are pytrees (`list[dict[str, Array]]`), not mutable `Parameter`
  objects (`layers.py:17-28`): grads are *returned*, the optimizer step is a
  pure function, and everything jits.
- The per-microbatch activation cache dicts (`layers.py:70,86,117,154`) become
  an explicit immutable **stash** pytree returned by `forward` and consumed by
  `backward` — the functional equivalent that lets GPipe keep several
  microbatches in flight, and lets `jax.checkpoint`-style rematerialisation
  apply if wanted.
- Deterministic dims-keyed init (`layers.py:104-113`): each Linear's weights
  are drawn from `MT19937(SeedSequence(in_dims + out_dims * 1337))` on the
  host, so every stage of every (DP, PP) partitioning reconstructs identical
  weights — the load-bearing property for parallelism-equivalence tests.
- Stage slicing with one-dim overlap and last-stage Softmax+MSELoss
  (`layers.py:236-270`).

The backward contract matches the reference's manual autograd: gradients are
summed over microbatches (`layers.py:135-136`), the last stage's backward takes
the *target* (its `MSELoss` head turns it into the first upstream gradient,
`layers.py:157-163`), and loss scaling is by global batch size.
"""

from __future__ import annotations

from typing import Any

import jax
import jax.numpy as jnp
import numpy as np
from numpy.random import MT19937, RandomState, SeedSequence

from shallowspeed_tpu.ops import functional as F

StageParamsT = list[dict[str, jax.Array]]


def stage_layer_sizes(sizes: list[int], stage_idx: int, n_stages: int) -> list[int]:
    """The layer-size slice owned by `stage_idx`, overlapping one boundary dim.

    Reference: `layers.py:242-250` — requires `len(sizes) % n_stages == 0`;
    each stage takes `stage_size` consecutive sizes plus the next boundary, so
    interior stages own `stage_size` Linears and the last stage one fewer.
    """
    assert len(sizes) % n_stages == 0, (len(sizes), n_stages)
    stage_size = len(sizes) // n_stages
    lo = stage_idx * stage_size
    hi = min(len(sizes), lo + stage_size + 1)
    return sizes[lo:hi]


def init_linear_np(in_dims: int, out_dims: int) -> dict[str, np.ndarray]:
    """Host-side deterministic init for one Linear, keyed only by its dims.

    Reference: `layers.py:104-113`. Identical weights regardless of how the
    model is partitioned across stages/replicas.
    """
    rs = RandomState(MT19937(SeedSequence(in_dims + out_dims * 1337)))
    w = (rs.normal(0.0, 1.0, (out_dims, in_dims)).astype(np.float32)
         / np.sqrt(in_dims)).astype(np.float32)
    b = np.zeros((1, out_dims), dtype=np.float32)
    return {"W": w, "b": b}


def init_stage_params(
    sizes: list[int], stage_idx: int = 0, n_stages: int = 1
) -> StageParamsT:
    """Parameter pytree for one pipeline stage (host numpy; `jax.device_put`
    or sharding-aware placement happens at the caller)."""
    local = stage_layer_sizes(sizes, stage_idx, n_stages)
    return [init_linear_np(local[i], local[i + 1]) for i in range(len(local) - 1)]


def zero_grads_like(params: Any) -> Any:
    """Fresh zero gradient pytree (replaces `Parameter.grad.fill(0)`,
    `layers.py:59-61`)."""
    return jax.tree_util.tree_map(jnp.zeros_like, params)


def accumulate_grads(acc: Any, new: Any) -> Any:
    """Sum-accumulate gradients across microbatches (`layers.py:135-136`)."""
    return jax.tree_util.tree_map(jnp.add, acc, new)


class MLPStage:
    """One pipeline stage of the partitioned MLP, as static metadata + pure fns.

    Pure-functional re-design of `MLP(Sequential)` (`layers.py:236-270`): the
    object holds only *static* structure (sizes, flags) so its `forward` /
    `backward` can be jitted once per stage; all numeric state (params, stash)
    flows through arguments and return values.

    Interior stage: [Linear+ReLU] * k.
    Last stage:     [Linear+ReLU] * (k-1), Linear (no act), Softmax, MSELoss
                    (`layers.py:251-263`). `MSELoss.forward` is the identity
                    (the loss value is never needed for the gradient,
                    `layers.py:150-155`), so the stage's forward output is the
                    softmax probabilities.
    """

    def __init__(self, sizes: list[int], stage_idx: int, n_stages: int,
                 batch_size: int):
        self.sizes = list(sizes)
        self.stage_idx = stage_idx
        self.n_stages = n_stages
        self.batch_size = batch_size  # GLOBAL batch size (`layers.py:237-241`)
        self.local_sizes = stage_layer_sizes(sizes, stage_idx, n_stages)
        self.is_first_stage = stage_idx == 0
        self.is_last_stage = stage_idx == n_stages - 1
        self.n_linears = len(self.local_sizes) - 1
        # Buffer-sizing surface used by the pipeline executor
        # (`layers.py:268-270`).
        self.in_dim = self.local_sizes[0]
        self.out_dim = self.local_sizes[-1]

    # -- init ------------------------------------------------------------
    def init(self) -> StageParamsT:
        return init_stage_params(self.sizes, self.stage_idx, self.n_stages)

    # -- pure forward/backward (jittable) --------------------------------
    def forward(self, params: StageParamsT, x: jax.Array):
        """Returns (out, stash).

        stash structure (static per stage): one entry per Linear —
        `{"x": input}` plus `{"mask": relu bitmask}` when the Linear has a
        ReLU — and for the last stage a trailing `{"logits", "probs"}` entry
        for the Softmax/MSELoss heads. This is the functional analogue of the
        `_cache[f"input_{mubatch_id}"]` dicts (`layers.py:70,86,117,154`).
        """
        stash = []
        h = x
        for i, layer in enumerate(params):
            entry = {"x": h}
            h = F.linear(h, layer["W"], layer["b"])
            has_relu = not (self.is_last_stage and i == self.n_linears - 1)
            if has_relu:
                entry["mask"] = h > 0
                h = F.relu(h)
            stash.append(entry)
        if self.is_last_stage:
            logits = h
            h = F.softmax(logits)
            stash.append({"logits": logits, "probs": h})
        return h, stash

    def infer(self, params: StageParamsT, x: jax.Array) -> jax.Array:
        """Eval-mode forward: no stash (mirrors `Module.eval()` disabling the
        cache, `layers.py:56-57,69,85,116`)."""
        out, _ = self.forward(params, x)
        return out

    def backward(self, params: StageParamsT, stash, dout: jax.Array):
        """Returns (dx, grads). `grads` matches the `params` pytree structure.

        On the last stage `dout` is the **target** one-hot batch: the MSELoss
        head converts it into the upstream gradient
        (`mse_loss_grad(probs, target, global_bs)`, `layers.py:157-163`), then
        Softmax's VJP recomputes from stashed logits (`layers.py:89-93`).
        Reversed-layer traversal mirrors `Sequential.backward`
        (`layers.py:201-213`).
        """
        if self.is_last_stage:
            head = stash[-1]
            dout = F.mse_loss_grad(head["probs"], dout, self.batch_size)
            dout = F.softmax_grad(dout, head["logits"])
        grads: list[dict[str, jax.Array] | None] = [None] * self.n_linears
        for i in range(self.n_linears - 1, -1, -1):
            entry = stash[i]
            if "mask" in entry:
                dout = F.relu_grad(dout, entry["mask"])
            dout, dw, db = F.linear_grad(dout, entry["x"], params[i]["W"])
            grads[i] = {"W": dw, "b": db}
        return dout, grads

    def loss(self, params: StageParamsT, x: jax.Array, target: jax.Array):
        """MSE loss value (global-batch-size scaled). Only valid on the last
        stage of a 1-stage model or fed with last-stage inputs."""
        out, _ = self.forward(params, x)
        return F.mse_loss(out, target, self.batch_size)

    def __repr__(self):
        layers = []
        for i in range(self.n_linears):
            act = "relu" if not (self.is_last_stage and i == self.n_linears - 1) else None
            layers.append(
                f"Linear({self.local_sizes[i]}->{self.local_sizes[i+1]}, act: {act})"
            )
        if self.is_last_stage:
            layers += ["Softmax()", "MSELoss()"]
        return f"MLPStage[{self.stage_idx}/{self.n_stages}]({', '.join(layers)})"
