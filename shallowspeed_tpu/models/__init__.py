from shallowspeed_tpu.models.mlp import (  # noqa: F401
    MLPStage,
    accumulate_grads,
    init_stage_params,
    stage_layer_sizes,
    zero_grads_like,
)
from shallowspeed_tpu.models.transformer import (  # noqa: F401
    TransformerConfig,
)
