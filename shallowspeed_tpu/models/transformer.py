"""Decoder-only transformer LM — the long-context model family.

The reference's model zoo is a single attention-free MLP
(`/root/reference/shallowspeed/layers.py:236-270`); this family extends the
framework to sequence models, designed TPU-first from the start:

- Pure-functional: `init(rng) -> params pytree`, `forward(params, tokens) ->
  logits`, `loss(params, tokens, targets)`; autograd is `jax.grad` (no
  hand-written VJPs here — the MLP family keeps those for reference parity,
  this family uses the idiomatic JAX transform).
- The attention implementation is pluggable: the same block runs full
  `attention` on one device or `ring_attention` over a sequence-sharded mesh
  axis (`shallowspeed_tpu/ops/attention.py`) — which is what makes context
  parallelism a property of the *mesh*, not of the model code.
- Pre-LN blocks, GELU MLP (4x), learned positional embeddings, weight-tied
  head kept separate (untied) for sharding simplicity; all matmul-heavy, so
  every FLOP lands on the MXU. bfloat16-friendly: compute dtype is a config
  knob, accumulations stay float32 inside attention.
"""

from __future__ import annotations

from dataclasses import dataclass
from functools import partial

import jax
import jax.numpy as jnp
import numpy as np
from jax.ad_checkpoint import checkpoint_name as _checkpoint_name

from shallowspeed_tpu.ops.attention import attention
from shallowspeed_tpu.ops.moe import moe_ffn


@dataclass(frozen=True)
class TransformerConfig:
    vocab: int = 256
    d_model: int = 128
    n_heads: int = 4
    n_layers: int = 2
    max_seq: int = 1024
    dtype: np.dtype = np.float32
    # Mixed precision: params stay in `dtype` (float32 master weights, and
    # the optimizer state with them); the forward pass casts them — and all
    # activations — to `compute_dtype` so matmuls run as bf16 MXU passes.
    # Stability-critical reductions stay float32 no matter what: layernorm
    # statistics, attention scores/softmax (`ops/attention.py`), the MoE
    # router (`ops/moe.py`), and the final log-softmax in `loss`. Gradients
    # come out float32 (the transpose of the param cast converts back).
    # None = compute in the param dtype (pure float32 training).
    compute_dtype: object = None
    # Rematerialization: recompute each block's activations in the backward
    # instead of storing them (jax.checkpoint around every block). Trades
    # ~1 extra forward of FLOPs for O(n_layers) -> O(1) activation memory —
    # the standard long-context lever on HBM-bound TPUs.
    remat: bool = False
    # What the per-block checkpoint SAVES (only read when remat=True):
    # - "full": save nothing, recompute the whole block (max memory saving,
    #   +~1 forward of FLOPs — the round-2 behavior).
    # - "attn": save each block's attention output (tagged "attn_out"
    #   below) — the backward replays the cheap projections/FFN but never
    #   re-runs the attention substrate (the flash kernel's forward is the
    #   expensive, bandwidth-bound part of the replay). +(B,T,d) bf16 per
    #   block.
    # - "dots": save every matmul output AND the attention output;
    #   backward recomputes only elementwise ops (norms, gelu/silu,
    #   rotary). Near-zero recompute FLOPs at ~14*d bytes/token per block
    #   — the right point when activations fit (e.g. microbatched big
    #   models); "full" remains the extreme-length fallback.
    remat_policy: str = "full"
    # Rotary position embeddings (Su et al., RoFormer): rotate q/k by
    # per-position phases inside every block instead of adding a learned
    # absolute embedding (pos_emb is kept in the pytree for structural
    # stability across engines but NOT added when rope is on). Positions
    # are global, so RoPE composes with sequence sharding unchanged: each
    # device rotates its local q/k block by its global positions before
    # the ring/all-to-all ever moves K.
    rope: bool = False
    rope_theta: float = 10000.0
    # Block options: normalization ("layernorm" | "rmsnorm") and dense FFN
    # flavor ("gelu" | "swiglu"). SwiGLU adds a "gate" projection per block
    # (column-sharded like "up" under tensor parallelism); MoE configs
    # (n_experts > 0) replace the dense FFN entirely and ignore `ffn`.
    norm: str = "layernorm"
    ffn: str = "gelu"
    # Grouped-query attention (Ainslie et al., GQA): n_kv_heads < n_heads
    # K/V heads, each shared by a group of n_heads/n_kv_heads query heads.
    # 0 = plain multi-head attention (the fused qkv projection). With GQA
    # the projection splits into "q" and "kv" params; K/V are repeated to
    # the full head count right before the attention op (so every
    # attention substrate works unchanged), but the decode KV cache stores
    # the UNREPEATED heads — its memory shrinks by the group factor.
    n_kv_heads: int = 0
    # Mixture-of-experts (0 = dense FFN everywhere). With n_experts > 0 every
    # block's FFN becomes a top-k routed MoE (`ops/moe.py`) — the family the
    # reference lacks entirely (SURVEY §2: EP absent).
    n_experts: int = 0
    moe_top_k: int = 2
    moe_capacity_factor: float = 2.0
    moe_aux_weight: float = 1e-2
    # MoE slot-assignment order: "sequence" (GShard: earlier tokens claim
    # an overflowing expert's slots) or "priority" (V-MoE batch-priority:
    # highest-gate assignments claim slots — drops hit the router's
    # least-confident choices instead of late-sequence tokens).
    moe_routing: str = "sequence"
    # Router z-loss weight (ST-MoE): penalizes router-logit magnitude —
    # the standard stabilizer for long MoE runs. 0 = off (default, so
    # existing trajectories are bit-unchanged); 1e-3 is the usual value.
    # Independent of moe_aux_weight (z-loss-only configs are fine).
    moe_z_weight: float = 0.0
    # Weight tying (Press & Wolf): the output head reuses tok_emb^T
    # instead of its own (vocab, d) matrix — the params pytree simply has
    # no "head" entry, so every engine's placement/checkpoint logic stays
    # structural. Standard for small/medium LMs; halves embedding memory.
    tie_embeddings: bool = False
    # Label smoothing (Szegedy et al.): mix the one-hot target with the
    # uniform distribution — loss = (1-ls)*NLL + ls*mean(-logp).
    label_smoothing: float = 0.0
    # Sliding-window (local) attention, Mistral-style: position i sees
    # only [i - attn_window + 1, i]. 0 = full causal attention. Composes
    # with GQA/rope/remat and the XLA-attention engines (plain dp, the
    # GSPMD family, the pipeline); the fused/resharded substrates
    # (flash, ring, ulysses) reject it. The decode cache applies the
    # same window, so sampling sees the trained distribution.
    attn_window: int = 0
    # Final-logit soft-capping (Gemma 2): logits <- cap*tanh(logits/cap)
    # bounds the head's output, taming loss spikes late in training.
    # Applied wherever head logits are produced (training loss AND
    # decode), so sampling sees the distribution that was trained.
    # 0 = off; Gemma 2 uses 30.0.
    logit_softcap: float = 0.0
    # Dropout rate on the embedding sum, each attention output, and each
    # FFN output (GPT-2 placement; attention-probability dropout is
    # deliberately omitted — it would not compose with the fused
    # flash/ring substrates). Active only when a `dropout_key` is
    # threaded into the forward: training steps pass a per-step key,
    # eval/decode paths pass None, so train/eval mode is a property of
    # the CALL, not of mutable model state (contrast the reference's
    # `Module.train()/eval()` flag, `layers.py:56-64`). Keys are derived
    # deterministically from (step, microbatch, layer), which makes the
    # masks reproducible under remat and 1F1B vjp recompute.
    dropout: float = 0.0
    # ATTENTION-PROBABILITY dropout (the classic pre-AV-matmul mask —
    # round-2 deliberately shipped only projection-output dropout and
    # the verdict flagged the silent semantics gap). Supported on the
    # plain XLA attention substrate only; configs selecting a fused or
    # resharded substrate (flash/ring at sp>1/ulysses/pipeline) are
    # rejected at build time rather than silently ignoring the rate.
    # Same train/eval contract as `dropout`: active only when a
    # dropout_key is threaded in.
    attn_dropout: float = 0.0
    # FFN hidden width; 0 = the classic 4*d_model. One knob shared by
    # init, the forward, and the FLOPs accounting (`flops.py`) so the
    # three can never drift.
    d_ff: int = 0
    # Chunked (blockwise) cross-entropy: compute the loss in chunks of
    # this many token positions, rematerializing each chunk's logits in
    # the backward — the (B*T, vocab) logits/log-probabilities are never
    # materialized or stored at once. 0 = classic whole-batch
    # log-softmax. Essential for large-vocab configs: at vocab 32k,
    # B*T=8k the classic path writes a ~1GB f32 log-prob residual;
    # chunked keeps O(chunk * vocab) transients only.
    xent_chunk: int = 0
    # fp8-e4m3 forward matmuls (round 18 — ROADMAP item 5's runtime
    # rung reaching the transformer): every dense projection (qkv/
    # q+kv, proj, up/down/gate, the untied head) runs
    # `ops.matmul.fp8_dense` — activations quantized with a
    # just-in-time per-tensor stop_gradient scale, weights with the
    # per-out-channel scale, f32 accumulation, straight-through
    # backward. Embeddings, norms and MoE banks stay in compute_dtype
    # (same exclusions as `quantize_weights`). The attribution gate
    # (bench.py's fp8 case) pins that this flag shrinks
    # attrib_mxu_frac vs the bf16 baseline while shadow parity holds.
    fp8_dense: bool = False

    def __post_init__(self):
        assert self.norm in ("layernorm", "rmsnorm"), self.norm
        assert self.ffn in ("gelu", "swiglu"), self.ffn
        assert self.moe_routing in ("sequence", "priority"), \
            self.moe_routing
        assert self.remat_policy in ("full", "attn", "dots"), \
            self.remat_policy
        assert self.xent_chunk >= 0, self.xent_chunk
        assert 0.0 <= self.dropout < 1.0, self.dropout
        assert 0.0 <= self.attn_dropout < 1.0, self.attn_dropout
        assert 0.0 <= self.label_smoothing < 1.0, self.label_smoothing
        assert self.attn_window >= 0, self.attn_window
        assert self.n_kv_heads >= 0, (
            f"n_kv_heads must be non-negative, got {self.n_kv_heads}")
        assert self.n_heads % self.kv_heads == 0, (
            f"n_heads={self.n_heads} must be divisible by "
            f"n_kv_heads={self.kv_heads}")
        # typed, not an assert: this gates a production precision mode
        if self.fp8_dense and _FP8_DTYPE is None:
            raise ValueError(
                "fp8_dense=True needs float8_e4m3fn support in this "
                "jax/XLA build; train in bf16/f32 instead")

    @property
    def head_dim(self) -> int:
        assert self.d_model % self.n_heads == 0
        return self.d_model // self.n_heads

    @property
    def ffn_dim(self) -> int:
        return self.d_ff or 4 * self.d_model

    @property
    def kv_heads(self) -> int:
        return self.n_kv_heads or self.n_heads

    @property
    def gqa(self) -> bool:
        return self.kv_heads != self.n_heads


def _dense_init(rng, in_d, out_d, dtype):
    w = rng.normal(0.0, 1.0 / np.sqrt(in_d), (in_d, out_d)).astype(dtype)
    return {"W": w, "b": np.zeros((out_d,), dtype)}


def init(cfg: TransformerConfig, seed: int = 0):
    """Host-side deterministic init (seeded like the MLP family's
    dims-keyed init, `layers.py:104-113`, but one seed for the whole tree)."""
    rng = np.random.default_rng(seed)
    dt = cfg.dtype
    d = cfg.d_model
    blocks = []
    for _ in range(cfg.n_layers):
        blk = {
            "ln1": {"g": np.ones((d,), dt), "b": np.zeros((d,), dt)},
            "proj": _dense_init(rng, d, d, dt),
            "ln2": {"g": np.ones((d,), dt), "b": np.zeros((d,), dt)},
        }
        if cfg.gqa:  # separate q and (smaller) fused kv projections
            blk["q"] = _dense_init(rng, d, d, dt)
            blk["kv"] = _dense_init(
                rng, d, 2 * cfg.kv_heads * cfg.head_dim, dt)
        else:
            blk["qkv"] = _dense_init(rng, d, 3 * d, dt)
        if cfg.ffn == "swiglu" and cfg.n_experts == 0:
            blk["gate"] = _dense_init(rng, d, cfg.ffn_dim, dt)
        if cfg.n_experts > 0:
            e, ff = cfg.n_experts, cfg.ffn_dim
            blk["moe"] = {
                "gate": rng.normal(0.0, 0.02, (d, e)).astype(dt),
                "wi": rng.normal(0.0, 1.0 / np.sqrt(d), (e, d, ff)).astype(dt),
                "bi": np.zeros((e, ff), dt),
                "wo": rng.normal(0.0, 1.0 / np.sqrt(ff), (e, ff, d)).astype(dt),
                "bo": np.zeros((e, d), dt),
            }
        else:
            blk["up"] = _dense_init(rng, d, cfg.ffn_dim, dt)
            blk["down"] = _dense_init(rng, cfg.ffn_dim, d, dt)
        blocks.append(blk)
    out = {
        "tok_emb": rng.normal(0.0, 0.02, (cfg.vocab, d)).astype(dt),
        "pos_emb": rng.normal(0.0, 0.02, (cfg.max_seq, d)).astype(dt),
        "blocks": blocks,
        "ln_f": {"g": np.ones((d,), dt), "b": np.zeros((d,), dt)},
    }
    if not cfg.tie_embeddings:
        out["head"] = _dense_init(rng, d, cfg.vocab, dt)
    return out


_NORM_KEYS = {"ln1", "ln2", "ln_f"}

# Quantized weight-storage leaves (see `quantize_weights`): "Wq" is the
# int8/fp8 value tensor, "Ws" the per-out-channel f32 scales. Both stay
# in their STORAGE dtype through `cast_params` — casting Wq would
# materialize the full-size dequantized copy the storage exists to
# avoid (the analysis `dequant-fusion` rule), and casting Ws to bf16
# would quantize the scales for no byte win (they are O(N), not O(K*N)).
_QUANT_KEYS = {"Wq", "Ws"}

WEIGHT_QUANT_MODES = ("", "int8", "fp8")

# fp8 weight storage uses e4m3 where this jax/XLA build ships it;
# otherwise `quantize_weights("fp8")` raises rather than silently
# storing something else.
_FP8_DTYPE = getattr(jnp, "float8_e4m3fn", None)


def quantize_weights(params, mode: str):
    """Quantize every dense projection's weight matrix for the decode
    path: each {"W": (K, N), "b"} dict in the pytree (block q/kv/qkv,
    proj, up/down/gate, the untied head) becomes {"Wq": (K, N) int8 or
    fp8-e4m3, "Ws": (N,) f32 per-out-channel scales, "b"}. Consumers
    dispatch on the "Wq" leaf (`_dense`) and run the fused-dequant
    matmul (`ops.matmul.dequant_matmul`) — the scale lands on the f32
    accumulator, the weight is read at 1 byte/element.

    Deliberately NOT quantized: embeddings (their decode read is one
    gathered row per token, not a sweep), norm scales and biases
    (O(d) — noise next to the matrices), and MoE expert banks (no
    serving path yet; ROADMAP item 5 extends this to training).
    Symmetric per-out-channel absmax scaling; mode "" returns the tree
    unchanged. A typed error, not an assert — this gates a production
    storage layout."""
    if mode not in WEIGHT_QUANT_MODES:
        raise ValueError(
            f"unsupported weight_quant={mode!r}; expected one of "
            f"{WEIGHT_QUANT_MODES} ('' = weights in the master dtype)")
    if not mode:
        return params
    if mode == "fp8" and _FP8_DTYPE is None:
        raise ValueError(
            "weight_quant='fp8' needs float8_e4m3fn support in this "
            "jax/XLA build; use 'int8'")

    def quant_dense(p):
        w = jnp.asarray(p["W"], jnp.float32)
        amax = jnp.maximum(jnp.max(jnp.abs(w), axis=0), 1e-8)   # (N,)
        if mode == "int8":
            ws = amax / 127.0
            wq = jnp.clip(jnp.round(w / ws), -127, 127).astype(jnp.int8)
        else:  # e4m3: max normal is 448
            ws = amax / 448.0
            wq = (w / ws).astype(_FP8_DTYPE)
        rest = {k: v for k, v in p.items() if k != "W"}
        return {"Wq": wq, "Ws": ws.astype(jnp.float32), **rest}

    def walk(node):
        if isinstance(node, dict):
            if "W" in node and np.ndim(node["W"]) == 2:
                return quant_dense(node)
            if "Wq" in node:          # already quantized: idempotent
                return node
            return {k: walk(v) for k, v in node.items()}
        if isinstance(node, list):
            return [walk(v) for v in node]
        return node

    return walk(params)


def weight_quant_mode(params) -> str:
    """The storage mode of a (possibly) quantized tree: "int8"/"fp8"
    when `quantize_weights` leaves are present, else ""."""
    for leaf in jax.tree_util.tree_leaves(params):
        if leaf.dtype == jnp.int8 and leaf.ndim == 2:
            return "int8"
        if _FP8_DTYPE is not None and leaf.dtype == _FP8_DTYPE:
            return "fp8"
    return ""


def cast_params(params, compute_dtype):
    """Mixed-precision boundary: float leaves to `compute_dtype` (None =
    identity; casting twice is free — same-dtype astype returns the
    operand). Shared by training forward and the decode path.

    Norm parameters (ln1/ln2/ln_f) stay in the MASTER dtype: every
    consumer immediately recasts them to f32 for the statistics
    (`_layernorm`/`_rmsnorm`, `zb.norm_fwd`), so a bf16 cast here would
    only quantize the scales and pay a dead f32->bf16->f32 round trip
    per use — the `analysis` dtype rule's round-trip finding (round 6).
    Norm OUTPUTS are cast to the activation dtype as before, so every
    matmul's operand dtypes are unchanged.

    Quantized-storage leaves (Wq/Ws, `quantize_weights`) likewise stay
    put: int8 is non-floating anyway, but fp8-e4m3 IS floating and a
    blanket cast would silently rewiden it to bf16 — the full-size
    dequantized copy the `dequant-fusion` analysis rule exists to
    catch; the f32 scales are numerics, not bulk bytes."""
    if compute_dtype is None:
        return params

    def cast(path, p):
        keys = {getattr(k, "key", None) for k in path}
        if keys & _NORM_KEYS or keys & _QUANT_KEYS:
            return p
        return (p.astype(compute_dtype)
                if jnp.issubdtype(p.dtype, jnp.floating) else p)

    return jax.tree_util.tree_map_with_path(cast, params)


def _layernorm(p, x, eps=1e-5):
    """Statistics in float32 (bf16 mean/variance loses too much precision);
    result back in x's dtype. No-op casts under pure-f32 training."""
    xf = x.astype(jnp.float32)
    mu = xf.mean(axis=-1, keepdims=True)
    var = ((xf - mu) ** 2).mean(axis=-1, keepdims=True)
    y = ((xf - mu) * jax.lax.rsqrt(var + eps) * p["g"].astype(jnp.float32)
         + p["b"].astype(jnp.float32))
    return y.astype(x.dtype)


def _rmsnorm(p, x, eps=1e-5):
    """RMSNorm (Zhang & Sennrich): scale by the root-mean-square only —
    no centering, no bias (p["b"] is kept in the pytree for structural
    stability but unused). f32 statistics like `_layernorm`."""
    xf = x.astype(jnp.float32)
    ms = (xf * xf).mean(axis=-1, keepdims=True)
    y = xf * jax.lax.rsqrt(ms + eps) * p["g"].astype(jnp.float32)
    return y.astype(x.dtype)


def _norm(p, x, cfg: TransformerConfig):
    return (_rmsnorm if cfg.norm == "rmsnorm" else _layernorm)(p, x)


def _dense(p, x, fp8: bool = False):
    if "Wq" in p:  # quantized storage (`quantize_weights`): the scale
        #            lands on the f32 accumulator, never on the weight
        from shallowspeed_tpu.ops.matmul import dequant_matmul

        return dequant_matmul(x, p["Wq"], p["Ws"]) + p["b"]
    if fp8:  # cfg.fp8_dense: the training-time quantized matmul. The
        #      activation scale is just-in-time per-tensor (unlike the
        #      Fp8TrainEngine's delayed history — a stateless model
        #      function has nowhere to carry one) and stop_gradient:
        #      the clip is exact-in-range by construction, so the
        #      analysis range rule holds without calibration state.
        from shallowspeed_tpu.ops.matmul import E4M3_MAX, fp8_dense

        w = p["W"]
        x2 = x.reshape(-1, x.shape[-1]).astype(jnp.float32)
        amax = jax.lax.stop_gradient(jnp.max(jnp.abs(x2)))
        sx = jnp.maximum(amax / E4M3_MAX, 1e-12)
        out = fp8_dense(x2, w.astype(jnp.float32), sx)
        return (out.reshape(*x.shape[:-1], w.shape[-1]).astype(x.dtype)
                + p["b"])
    return x @ p["W"] + p["b"]


def _dropout(x, rate: float, key):
    """Inverted dropout; identity when `key` is None or rate is 0 (the
    static no-op keeps eval/decode traces free of RNG ops)."""
    if key is None or rate == 0.0:
        return x
    keep = 1.0 - rate
    mask = jax.random.bernoulli(key, keep, x.shape)
    return jnp.where(mask, x / keep, 0.0).astype(x.dtype)


def head_logits(params, x, cfg: TransformerConfig):
    """Vocabulary projection: the untied head, or tok_emb^T when
    cfg.tie_embeddings (no bias — the tied head has none); optionally
    soft-capped (`cfg.logit_softcap`), in f32 so tanh saturation is not
    computed in bf16."""
    logits = (x @ params["tok_emb"].T if cfg.tie_embeddings
              else _dense(params["head"], x, cfg.fp8_dense))
    if cfg.logit_softcap > 0.0:
        cap = cfg.logit_softcap
        logits = cap * jnp.tanh(logits.astype(jnp.float32) / cap)
    return logits


def token_loss(logits, targets, cfg: TransformerConfig,
               train: bool = True):
    """Mean token cross-entropy in float32, with optional label
    smoothing. THE loss every engine computes (the pipeline engines call
    it per microbatch), so smoothing/vocab changes happen in one place.
    Smoothing is a TRAINING regularizer: eval paths pass train=False so
    reported val loss/perplexity stays the plain NLL, comparable across
    runs regardless of --label-smoothing."""
    logp = jax.nn.log_softmax(logits.astype(jnp.float32), axis=-1)
    nll = -jnp.take_along_axis(logp, targets[..., None], axis=-1)[..., 0]
    ls = cfg.label_smoothing
    if train and ls > 0.0:
        nll = (1.0 - ls) * nll + ls * (-logp.mean(axis=-1))
    return nll.mean()


def _remat_policy(cfg: TransformerConfig):
    """jax.checkpoint policy for cfg.remat_policy (None = save nothing)."""
    cp = jax.checkpoint_policies
    if cfg.remat_policy == "attn":
        return cp.save_only_these_names("attn_out")
    if cfg.remat_policy == "dots":
        return cp.save_from_both_policies(
            cp.dots_with_no_batch_dims_saveable,
            cp.save_only_these_names("attn_out"))
    return None


def chunked_token_loss(params, x, targets, cfg: TransformerConfig,
                       train: bool = True):
    """`token_loss(head_logits(x))` without ever materializing the
    (B*T, vocab) logits: positions are processed in chunks of
    cfg.xent_chunk under a `lax.scan`, each chunk's logits/logsumexp
    rematerialized in the backward (`jax.checkpoint`), so peak memory is
    O(chunk * vocab) transients plus the scalar carry — vs the classic
    path's full f32 log-prob residual. Numerically it computes the SAME
    quantity (lse - target logit, f32 reductions over the same bf16
    logits), reassociated per chunk.

    `params` is the UNCAST tree; only the head leaves are cast here (XLA
    CSEs the duplicate cast against the forward's). `x` is the final-norm
    output (B, T, d)."""
    if cfg.tie_embeddings:
        hp = {"tok_emb": params["tok_emb"]}
    else:
        hp = {"head": params["head"]}
    hp = cast_params(hp, cfg.compute_dtype)
    b, t, d = x.shape
    total = b * t
    n = min(cfg.xent_chunk, total)
    xf = x.reshape(total, d)
    tf = targets.reshape(total)
    rem = (-total) % n
    ls = cfg.label_smoothing if train else 0.0
    if rem:  # pad to a whole number of chunks; mask the pad rows out
        xf = jnp.pad(xf, ((0, rem), (0, 0)))
        tf = jnp.pad(tf, (0, rem))
        wf = jnp.pad(jnp.ones((total,), jnp.float32), (0, rem))
    else:
        wf = jnp.ones((total,), jnp.float32)

    def chunk_nll(hp, xc, tc, wc):
        logits = head_logits(hp, xc, cfg).astype(jnp.float32)  # (n, V)
        lse = jax.scipy.special.logsumexp(logits, axis=-1)
        tgt = jnp.take_along_axis(logits, tc[:, None], axis=-1)[:, 0]
        nll = lse - tgt
        if ls > 0.0:
            # -mean logp = lse - mean(logits); same algebra as token_loss
            nll = (1.0 - ls) * nll + ls * (lse - logits.mean(axis=-1))
        return (nll * wc).sum()

    body = jax.checkpoint(chunk_nll)
    k = xf.shape[0] // n

    def sbody(acc, xs):
        return acc + body(hp, *xs), None

    # the accumulator must carry x's mesh-variance type (inside a
    # shard_map the per-chunk sums are device-varying; a plain 0.0 is
    # invariant and the scan would reject the carry) — deriving the
    # zero from x itself inherits the right type at zero cost
    acc0 = (xf[0, 0] * 0).astype(jnp.float32)
    tot, _ = jax.lax.scan(
        sbody, acc0,
        (xf.reshape(k, n, d), tf.reshape(k, n), wf.reshape(k, n)))
    return tot / total


def rope_rotate(x, pos, theta: float = 10000.0):
    """Apply rotary embeddings to (B, T, H, D) at global positions `pos`
    (shape (T,) int, or a scalar for single-token decode). Pairs dimension
    halves (d, d + D/2) — the half-split formulation; phases are f32 for
    long-sequence accuracy, result in x's dtype."""
    d = x.shape[-1]
    assert d % 2 == 0, f"rope needs an even head_dim, got {d}"
    half = d // 2
    freqs = theta ** (-jnp.arange(half, dtype=jnp.float32) / half)
    pos = jnp.atleast_1d(jnp.asarray(pos, jnp.float32))
    ang = pos[:, None] * freqs                               # (T, half)
    cos = jnp.cos(ang)[None, :, None, :]                     # (1, T, 1, half)
    sin = jnp.sin(ang)[None, :, None, :]
    xf = x.astype(jnp.float32)
    x1, x2 = xf[..., :half], xf[..., half:]
    out = jnp.concatenate([x1 * cos - x2 * sin,
                           x1 * sin + x2 * cos], axis=-1)
    return out.astype(x.dtype)


def _qkv(p, h, cfg: TransformerConfig):
    """(q (B,T,H,hd), k, v (B,T,Hkv,hd)) from the block's projection(s):
    the fused head-major qkv, or split q / fused kv under GQA."""
    b, t, _ = h.shape
    if "kv" in p:
        q = _dense(p["q"], h, cfg.fp8_dense).reshape(
            b, t, cfg.n_heads, cfg.head_dim)
        kv = _dense(p["kv"], h, cfg.fp8_dense).reshape(
            b, t, cfg.kv_heads, 2, cfg.head_dim)
        k, v = kv[..., 0, :], kv[..., 1, :]
    else:
        qkv = _dense(p["qkv"], h, cfg.fp8_dense).reshape(
            b, t, cfg.n_heads, 3, cfg.head_dim)
        q, k, v = qkv[:, :, :, 0], qkv[:, :, :, 1], qkv[:, :, :, 2]
    return q, k, v


def repeat_kv(x, cfg: TransformerConfig):
    """Broadcast K/V heads to the full query-head count (no-op for MHA).

    Only needed for attention substrates that predate native GQA; every
    substrate in `ops/attention.py` and `ops/flash_attention.py` declares
    `supports_gqa` and consumes the unrepeated heads directly (kernel
    q-row group folding / grouped einsums), so the hot paths never
    materialize the repeat — the group-factor saving covers compute and
    bandwidth, not just cache storage."""
    g = cfg.n_heads // cfg.kv_heads
    return x if g == 1 else jnp.repeat(x, g, axis=2)


def _supports_gqa(fn) -> bool:
    """Unwrap functools.partial layers to read a substrate's GQA tag."""
    while isinstance(fn, partial):
        fn = fn.func
    return bool(getattr(fn, "supports_gqa", False))


def _supports_prob_dropout(fn) -> bool:
    while isinstance(fn, partial):
        fn = fn.func
    return bool(getattr(fn, "supports_prob_dropout", False))


def _ffn(p, x, cfg: TransformerConfig, h, key=None):
    """Post-attention half of a block: FFN (dense GELU, SwiGLU, or routed
    MoE) on the norm output `h`, dropout, residual onto `x`.
    Returns (x, (balance aux, router z-loss)) — both unweighted; `loss`
    owns the weights (so a z-loss-only or balance-only config needs no
    coupling between the two)."""
    if "moe" in p:
        y, aux, z, st = moe_ffn(p["moe"], h, cfg.moe_top_k,
                                cfg.moe_capacity_factor,
                                priority=cfg.moe_routing == "priority")
        return x + _dropout(y, cfg.dropout, key), (aux, z, st)
    if "gate" in p:  # SwiGLU: silu(gate) * up, both column-parallel
        u = jax.nn.silu(_dense(p["gate"], h, cfg.fp8_dense)) \
            * _dense(p["up"], h, cfg.fp8_dense)
    else:
        u = jax.nn.gelu(_dense(p["up"], h, cfg.fp8_dense))
    return (x + _dropout(_dense(p["down"], u, cfg.fp8_dense),
                         cfg.dropout, key),
            (0.0, 0.0, None))


def _block(p, x, cfg: TransformerConfig, attn_fn, with_kv: bool = False,
           pos=None, key=None):
    """One pre-LN block; returns (x, aux) where aux is the MoE
    load-balancing loss (0.0 for dense blocks). With `with_kv` also
    returns this block's (k, v) — the decode prefill
    (`models/generate.py`) captures them into its cache; the training
    path never requests them, so XLA dead-code-eliminates the extra
    outputs there. `pos` (global positions) is required when cfg.rope.
    `key` (training only) seeds this block's attention/FFN dropout."""
    b, t, d = x.shape
    k_attn = k_ffn = k_prob = None
    if key is not None and cfg.dropout > 0.0 and cfg.attn_dropout > 0.0:
        k_attn, k_ffn, k_prob = jax.random.split(key, 3)
    elif key is not None and cfg.dropout > 0.0:
        # 2-way split kept for bit-compatibility with round-2 streams
        k_attn, k_ffn = jax.random.split(key)
    elif key is not None and cfg.attn_dropout > 0.0:
        k_prob = key
    h = _norm(p["ln1"], x, cfg)
    # head-major fused layout (H, 3, D): a contiguous slice of the 3d output
    # dim is a whole group of heads, so tensor-parallel column sharding of
    # qkv["W"] keeps attention fully local to each device (Megatron
    # alignment; see parallel/tensor.py). Under GQA, _qkv splits into
    # q / kv projections instead.
    q, k, v = _qkv(p, h, cfg)
    if cfg.rope:
        assert pos is not None, "cfg.rope needs positions threaded in"
        q = rope_rotate(q, pos, cfg.rope_theta)
        k = rope_rotate(k, pos, cfg.rope_theta)
    kv_cacheable = (k, v)  # rotated, UNREPEATED — the decode cache layout
    extra = {}
    if cfg.attn_dropout > 0.0:
        assert _supports_prob_dropout(attn_fn), (
            "cfg.attn_dropout needs the plain XLA attention substrate "
            "(fused flash / resharded ring/ulysses paths cannot mask "
            "probabilities inside their score blocks)")
        extra = {"dropout": cfg.attn_dropout, "dropout_key": k_prob}
    if _supports_gqa(attn_fn):  # native GQA: no repeated K/V materialized
        a = attn_fn(q, k, v, **extra).reshape(b, t, d)
    else:
        a = attn_fn(q, repeat_kv(k, cfg), repeat_kv(v, cfg),
                    **extra).reshape(b, t, d)
    # name for selective remat: cfg.remat_policy "attn"/"dots" saves this
    # value so the backward replay never re-runs the attention substrate
    # (no-op outside a policied jax.checkpoint)
    a = _checkpoint_name(a, "attn_out")
    x = x + _dropout(_dense(p["proj"], a, cfg.fp8_dense),
                     cfg.dropout, k_attn)
    h = _norm(p["ln2"], x, cfg)
    x, aux = _ffn(p, x, cfg, h, k_ffn)
    if with_kv:
        return x, aux, kv_cacheable
    return x, aux


def forward_with_aux(params, tokens, cfg: TransformerConfig,
                     attn_fn=None, pos_offset=0, dropout_key=None,
                     with_stats: bool = False, head: bool = True):
    """tokens: (batch, seq) int32 -> (logits (batch, seq, vocab), moe aux).

    `head=False` returns the final-norm hidden states (batch, seq, d)
    instead of logits — the chunked-cross-entropy path (`loss` with
    cfg.xent_chunk) applies the vocab projection itself, blockwise.

    With `with_stats=True` additionally returns layer-averaged MoE
    routing statistics ({"load": (E,), "drop_fraction": scalar}, or None
    for dense configs) as a third element — observability for the
    silent capacity drop (`ops/moe.py`); when unused, XLA dead-code-
    eliminates the accounting.

    `attn_fn(q, k, v)` defaults to full causal attention; a context-parallel
    caller passes `partial(ring_attention, axis_name='sp')` and the global
    `pos_offset` of its sequence block (positions are global under sequence
    sharding). `dropout_key` (training only) activates cfg.dropout; per-
    layer keys are fold_in-derived, so remat recompute sees identical
    masks.
    """
    if attn_fn is None:
        attn_fn = partial(attention, causal=True, window=cfg.attn_window)
    params = cast_params(params, cfg.compute_dtype)
    b, t = tokens.shape
    # Under jit an out-of-range gather silently clamps to pos_emb's last row;
    # guard statically where possible (pos_offset is traced in the
    # context-parallel path — the engine checks the global length instead).
    if isinstance(pos_offset, int):
        assert pos_offset + t <= cfg.max_seq, (
            f"sequence positions [{pos_offset}, {pos_offset + t}) exceed "
            f"max_seq={cfg.max_seq}")
    if cfg.dropout == 0.0 and cfg.attn_dropout == 0.0:
        dropout_key = None
    pos = pos_offset + jnp.arange(t)
    x = params["tok_emb"][tokens]
    if not cfg.rope:  # rope replaces the learned absolute embedding
        x = x + params["pos_emb"][pos]
    if dropout_key is not None:
        x = _dropout(x, cfg.dropout,
                     jax.random.fold_in(dropout_key, cfg.n_layers))
    aux_total, z_total = 0.0, 0.0
    stats_sum, n_moe = None, 0
    block_fn = _block
    if cfg.remat:
        block_fn = jax.checkpoint(_block, static_argnums=(2, 3, 4),
                                  policy=_remat_policy(cfg))
    for i, blk in enumerate(params["blocks"]):
        k_i = (None if dropout_key is None
               else jax.random.fold_in(dropout_key, i))
        x, (aux, z, st) = block_fn(blk, x, cfg, attn_fn, False, pos, k_i)
        aux_total = aux_total + aux
        z_total = z_total + z
        if st is not None:
            stats_sum = (st if stats_sum is None else
                         jax.tree_util.tree_map(jnp.add, stats_sum, st))
            n_moe += 1
    x = _norm(params["ln_f"], x, cfg)
    out = head_logits(params, x, cfg) if head else x
    if with_stats:
        stats = (None if stats_sum is None else jax.tree_util.tree_map(
            lambda v: v / n_moe, stats_sum))
        return out, (aux_total, z_total), stats
    return out, (aux_total, z_total)


def forward(params, tokens, cfg: TransformerConfig,
            attn_fn=None, pos_offset=0, dropout_key=None):
    """Logits only (see `forward_with_aux` for the MoE aux loss)."""
    return forward_with_aux(params, tokens, cfg, attn_fn, pos_offset,
                            dropout_key)[0]


def loss(params, tokens, targets, cfg: TransformerConfig,
         attn_fn=None, pos_offset=0, dropout_key=None, train: bool = True):
    """Mean softmax cross-entropy over all (batch, seq) positions, plus the
    weighted MoE load-balancing aux loss when the config has experts.

    Under data/sequence sharding the mean over the LOCAL block is returned;
    the caller averages across shards (`lax.pmean`) — exact because all
    blocks have equal size.
    """
    if cfg.xent_chunk > 0:
        hid, (aux, z) = forward_with_aux(params, tokens, cfg, attn_fn,
                                         pos_offset, dropout_key,
                                         head=False)
        tl = chunked_token_loss(params, hid, targets, cfg, train)
    else:
        logits, (aux, z) = forward_with_aux(params, tokens, cfg, attn_fn,
                                            pos_offset, dropout_key)
        tl = token_loss(logits, targets, cfg, train)
    total = tl + cfg.moe_aux_weight * aux
    if cfg.moe_z_weight > 0.0:
        total = total + cfg.moe_z_weight * z
    return total
