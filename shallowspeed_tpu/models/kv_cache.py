"""KV-cache primitives shared by batch decode and the serving runtime.

Round 11 refactor: `models/generate.py` owned these ops privately; the
serving subsystem (`shallowspeed_tpu/serving/` — paged block pools read
through a gathered block table) needs the SAME write/quantize/attend
math so paged decode provably matches the contiguous cache. The ops
moved here unchanged; `generate.py` re-exports them under its old
names, so its numerics (and every pinned stream) are bit-identical.

Layout contract (round 5, head-major): contiguous caches are
(B, Hkv, slots, hd) per block; the serving pools are
(n_blocks, Hkv, block_size, hd) — the SAME innermost (positions, hd)
sweep per (batch/block, head), so the decode read stays one contiguous
DMA per head whether the slots come from one buffer or a gathered
table. int8 caches ride one f32 scale per (row, head, position); the
scales stay OUTSIDE the attention einsums (K's multiplies the score,
V's folds into the probability row) so HBM reads remain int8.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from shallowspeed_tpu.models import transformer as T

KV_QUANT_MODES = ("", "int8")


def init_kv_cache(cfg: T.TransformerConfig, batch: int,
                  cache_len: int | None = None, kv_quant: str = ""):
    """Per-block K/V buffers (B, Hkv, cache_len, head_dim), zero-filled —
    under GQA the cache holds the UNREPEATED kv heads, shrinking its
    memory by the query-group factor.

    HEAD-MAJOR layout (round 5): the decode sweep reads one head's
    whole history per (batch, head) — with the old (B, S, Hkv, hd)
    layout those reads were hd*2 = 128-byte rows at an Hkv*hd*2-byte
    stride (sub-DMA-granularity: the b8 8k MHA sweep measured 257 GB/s
    vs the 819 GB/s roofline); head-major makes each (b, h) sweep one
    contiguous (S, hd) block. The per-token write transposes a
    (B, 1, Hkv, hd) slice — noise next to the read it fixes.

    `cache_len` defaults to cfg.max_seq; `generate` passes the SIZED
    length (prompt bucket + max_new) instead — decode is HBM-bound on
    the cache sweep, so a max_seq-sized buffer on a short generation
    pays bandwidth for slots that can never be read (round-4 decode
    hygiene, VERDICT r3).

    `kv_quant="int8"` (round 5 — the batched-long-context lever the
    round-4 roofline named): K/V store as int8 with one f32 scale per
    (batch, position, head); the cache sweep's bytes halve vs bf16.
    The scales ride OUTSIDE the attention einsums (K's scale multiplies
    the score, V's folds into the probability row), so HBM reads stay
    int8 — see `cached_attention`."""
    if kv_quant not in KV_QUANT_MODES:
        # a typed error, not an assert: asserts vanish under python -O,
        # and an unknown mode must fail loudly in production too
        raise ValueError(
            f"unsupported kv_quant={kv_quant!r}; expected one of "
            f"{KV_QUANT_MODES} ('' = cache in the compute dtype)")
    dt = cfg.compute_dtype or cfg.dtype
    shape = (batch, cfg.kv_heads, cache_len or cfg.max_seq, cfg.head_dim)
    if kv_quant:
        sshape = shape[:3] + (1,)
        return [{"k": jnp.zeros(shape, jnp.int8),
                 "k_s": jnp.zeros(sshape, jnp.float32),
                 "v": jnp.zeros(shape, jnp.int8),
                 "v_s": jnp.zeros(sshape, jnp.float32)}
                for _ in range(cfg.n_layers)]
    return [{"k": jnp.zeros(shape, dt), "v": jnp.zeros(shape, dt)}
            for _ in range(cfg.n_layers)]


def quantize_kv(x):
    """(values int8, scales f32): symmetric per-(b, head, t) absmax
    quantization over the head_dim axis (x: (B, Hkv, T, hd))."""
    xf = x.astype(jnp.float32)
    scale = jnp.max(jnp.abs(xf), axis=-1, keepdims=True) / 127.0
    scale = jnp.maximum(scale, 1e-8)
    q = jnp.clip(jnp.round(xf / scale), -127, 127).astype(jnp.int8)
    return q, scale


def cache_write(cache_blk, k, v, pos):
    """Write this slice's K/V at `pos` (k/v arrive token-major
    (B, T, Hkv, hd) from the block; the cache is head-major),
    quantizing when the cache is int8 (the scale leaves' presence is
    the dispatch)."""
    k = jnp.swapaxes(k, 1, 2)
    v = jnp.swapaxes(v, 1, 2)
    if "k_s" in cache_blk:
        kq, ks = quantize_kv(k)
        vq, vs = quantize_kv(v)
        upd = {"k": kq, "k_s": ks, "v": vq, "v_s": vs}
    else:
        upd = {"k": k.astype(cache_blk["k"].dtype),
               "v": v.astype(cache_blk["v"].dtype)}
    return {
        **cache_blk,
        **{name: jax.lax.dynamic_update_slice_in_dim(
            cache_blk[name], val, pos, axis=2)
           for name, val in upd.items()},
    }


def masked_attention(q, cache_blk, valid, cfg):
    """The cache-attention core: q (B, Tq, H, hd) attends over a
    head-major K/V view (B, Hkv, S, hd) under an explicit validity
    mask. `valid` is a boolean broadcastable against the
    (B, Hkv, G, Tq, S) score tensor — contiguous decode passes the
    position prefix (`cached_attention`), the serving runtime passes
    per-row masks over a gathered block table with the same math, so
    paged and contiguous logits can only differ by gather/fp-reorder
    noise (pinned to 1e-4 in tests/test_serving.py).

    GQA caches hold Hkv heads and are read UNREPEATED (grouped einsum):
    decode is HBM-bandwidth-bound on the cache sweep, so the group
    factor shrinks the per-step traffic, not just the cache footprint.
    Scores accumulate in f32; int8 caches keep their scales outside the
    einsums (K's on the score, V's folded into the probability row) so
    the HBM reads stay int8.
    """
    k, v = cache_blk["k"], cache_blk["v"]       # (B, Hkv, S, hd)
    b, tq, h, hd = q.shape
    kvh = k.shape[1]
    quant = "k_s" in cache_blk
    qg = q.reshape(b, tq, kvh, h // kvh, hd)
    scale = 1.0 / jnp.sqrt(jnp.float32(hd))
    if quant:
        # int8 sweep: the einsum reads int8 rows (the cast fuses into
        # the load; int8 values are EXACT in bf16, so the MXU runs at
        # its bf16 rate with f32 accumulation); K's per-(b, head, t)
        # scale is constant over hd, so it multiplies the SCORE
        # instead of dequantizing the cache
        cdt = cfg.compute_dtype or cfg.dtype
        s = jnp.einsum("bqhgd,bhkd->bhgqk", qg.astype(cdt),
                       k.astype(cdt),
                       preferred_element_type=jnp.float32)
        s = s * cache_blk["k_s"][..., 0][:, :, None, None, :]
        s = s * scale
    else:
        s = jnp.einsum("bqhgd,bhkd->bhgqk", qg, k,
                       preferred_element_type=jnp.float32) * scale
    s = jnp.where(valid, s, jnp.float32(-1e30))
    p = jax.nn.softmax(s, axis=-1)
    if quant:
        # V's scale varies along the summation index — fold it into the
        # (tiny) probability rows, keeping the V read int8
        cdt = cfg.compute_dtype or cfg.dtype
        pv = p * cache_blk["v_s"][..., 0][:, :, None, None, :]
        out = jnp.einsum("bhgqk,bhkd->bqhgd", pv.astype(cdt),
                         v.astype(cdt),
                         preferred_element_type=jnp.float32)
    else:
        out = jnp.einsum("bhgqk,bhkd->bqhgd", p.astype(v.dtype), v,
                         preferred_element_type=jnp.float32)
    return out.reshape(b, tq, h, hd).astype(q.dtype)


def position_mask(slots: int, pos, window: int = 0):
    """The contiguous-cache validity prefix: slots [0, pos] are live
    (the tail beyond `pos` is zeros — masked out by position, so its
    contents never matter), optionally windowed to the training mask's
    sliding window."""
    valid = jnp.arange(slots) <= pos
    if window > 0:
        valid = valid & (jnp.arange(slots) > pos - window)
    return valid


def cached_attention(q, cache_blk, pos, cfg):
    """q: (B, 1, H, hd) at position `pos`; attends over cache[:, :pos+1]
    — `masked_attention` under the contiguous position prefix."""
    valid = position_mask(cache_blk["k"].shape[2], pos, cfg.attn_window)
    return masked_attention(q, cache_blk,
                            valid[None, None, None, None, :], cfg)
