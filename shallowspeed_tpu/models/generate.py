"""Autoregressive decoding with a KV cache for the transformer LM family.

The reference's only inference surface is a forward-only pipeline schedule
over the MLP (`/root/reference/shallowspeed/pipe.py:275-294`); sequence
models need real decoding. Designed TPU-first:

- **Static shapes.** The KV cache is a fixed head-major
  (B, Hkv, cache_len, hd) buffer per block (sized to prompt bucket +
  max_new, not max_seq); the decode loop is one `lax.scan` over
  `max_new` steps — the whole generation compiles to a single XLA
  program, no per-token Python dispatch or retracing.
- **Parallel prefill.** The prompt runs through the normal batched
  forward (`_block(..., with_kv=True)` captures each block's K/V in one
  MXU-friendly pass); only the new tokens decode sequentially.
- **f32 score path.** Decode attention accumulates scores in f32 with a
  position mask over the not-yet-written cache tail, matching
  `ops/attention.py` numerics, so cached decoding reproduces the batched
  forward's logits exactly (tested to 1e-4).

Sampling: temperature (0 = greedy argmax), optional top-k truncation and/or
nucleus (top-p) filtering, with `jax.random` counter-based keys —
reproducible given a seed.
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp

from shallowspeed_tpu.models import transformer as T
from shallowspeed_tpu.models.kv_cache import (cache_write, cached_attention,
                                              init_kv_cache, quantize_kv)

# Round-11 refactor: the cache primitives moved to `models/kv_cache.py`
# so the serving runtime (`shallowspeed_tpu/serving/` — paged block
# pools) shares the exact write/quantize/attend math with this
# contiguous path. Old private names kept as aliases — the ops are
# UNCHANGED, so every pinned stream stays bit-identical.
_quantize_kv = quantize_kv
_cache_write = cache_write
_cached_attention = cached_attention


def _block_decode(p, x, cfg: T.TransformerConfig, cache_blk, pos):
    """One block on a single-token slice x (B, 1, d); writes this token's
    K/V at `pos` and attends over the cache. Returns (x, cache_blk)."""
    b = x.shape[0]
    h = T._norm(p["ln1"], x, cfg)
    q, k, v = T._qkv(p, h, cfg)
    if cfg.rope:  # rotate at this token's position; cache stores rotated K
        q = T.rope_rotate(q, pos, cfg.rope_theta)
        k = T.rope_rotate(k, pos, cfg.rope_theta)
    cache_blk = _cache_write(cache_blk, k, v, pos)
    a = _cached_attention(q, cache_blk, pos, cfg).reshape(b, 1, cfg.d_model)
    x = x + T._dense(p["proj"], a)
    h = T._norm(p["ln2"], x, cfg)
    x, _aux = T._ffn(p, x, cfg, h)
    return x, cache_blk


def _embed(params, tokens, pos0, cfg):
    t = tokens.shape[1]
    pos = pos0 + jnp.arange(t)
    x = params["tok_emb"][tokens]
    if not cfg.rope:  # rope replaces the learned absolute embedding
        x = x + params["pos_emb"][pos]
    if cfg.compute_dtype is not None:
        x = x.astype(cfg.compute_dtype)
    return x


def prefill(params, tokens, cfg: T.TransformerConfig, cache,
            last_idx=None, attn_impl: str = "xla"):
    """Batched forward over the prompt, capturing each block's K/V.

    tokens: (B, Tp). Returns (logits (B, vocab) in f32 at `last_idx`
    — default Tp-1; a TRACED index when the prompt is right-padded to
    a bucket length and the true last token sits earlier — and the
    filled cache). With padding, cache slots in [last_idx+1, Tp) hold
    pad-token garbage, but decode OVERWRITES slot p before reading it
    (the position mask admits only slots <= p), so the garbage is
    never consumed.

    `attn_impl="flash"` runs the blockwise Pallas kernel instead of
    XLA attention — long prompts OOM on the (B, H, Tp, Tp) f32 score
    materialization (an 8k b8 h16 prompt wants 32 GB of scores; the
    kernel streams tiles). `generate` auto-selects it at or past 2048
    prompt tokens (when the tile size survives the length)."""
    params = T.cast_params(params, cfg.compute_dtype)
    tp = tokens.shape[1]
    if cfg.attn_dropout > 0.0:
        # inference never drops (key=None makes it inert), but the
        # block's substrate-capability assert keys off cfg alone — a
        # model TRAINED with attn dropout must still prefill on any
        # substrate
        from dataclasses import replace as _replace

        cfg = _replace(cfg, attn_dropout=0.0)
    x = _embed(params, tokens, 0, cfg)
    if attn_impl == "flash":
        from shallowspeed_tpu.ops.flash_attention import flash_attention

        attn = partial(flash_attention, causal=True,
                       window=cfg.attn_window)
    else:
        attn = partial(T.attention, causal=True, window=cfg.attn_window)
    pos = jnp.arange(tp)
    for i, blk in enumerate(params["blocks"]):
        x, _aux, (k, v) = T._block(blk, x, cfg, attn, with_kv=True,
                                   pos=pos)
        cache[i] = _cache_write(cache[i], k, v, 0)
    x = T._norm(params["ln_f"], x, cfg)
    if last_idx is None:
        x_last = x[:, tp - 1]
    else:
        x_last = jax.lax.dynamic_index_in_dim(x, last_idx, 1, False)
    logits = T.head_logits(params, x_last, cfg)
    return logits.astype(jnp.float32), cache


def decode_step(params, token, pos, cache, cfg: T.TransformerConfig):
    """One cached decode step. token: (B,) int32 at position `pos`
    (traced scalar). Returns (logits (B, vocab) f32, updated cache).

    Callers in a loop should pre-cast params (`T.cast_params`) once; the
    cast here is then a same-dtype identity."""
    params = T.cast_params(params, cfg.compute_dtype)
    x = _embed(params, token[:, None], pos, cfg)
    new_cache = []
    for blk, cblk in zip(params["blocks"], cache):
        x, cblk = _block_decode(blk, x, cfg, cblk, pos)
        new_cache.append(cblk)
    x = T._norm(params["ln_f"], x, cfg)
    logits = T.head_logits(params, x[:, 0], cfg)
    return logits.astype(jnp.float32), new_cache


def filter_logits(logits, top_k: int, top_p: float):
    """Row-wise top-k then nucleus (top-p) support truncation on
    temperature-scaled logits (B, V). Shared by `_sample` and the
    serving engine's per-row sampler — ONE implementation, so the
    pinned serving-vs-`generate()` stream parity cannot drift on
    filtered runs."""
    if top_k > 0:
        kth = jax.lax.top_k(logits, top_k)[0][:, -1:]       # (B, 1)
        logits = jnp.where(logits < kth, -jnp.inf, logits)
    if 0.0 < top_p < 1.0:
        # nucleus: keep the smallest prefix of the sorted distribution
        # whose mass reaches top_p (the first token always survives)
        sort_idx = jnp.argsort(-logits, axis=-1)
        sorted_logits = jnp.take_along_axis(logits, sort_idx, axis=-1)
        probs = jax.nn.softmax(sorted_logits, axis=-1)
        cum = jnp.cumsum(probs, axis=-1)
        keep_sorted = (cum - probs) < top_p      # mass BEFORE this token
        keep = jnp.zeros_like(keep_sorted).at[
            jnp.arange(logits.shape[0])[:, None], sort_idx].set(keep_sorted)
        logits = jnp.where(keep, logits, -jnp.inf)
    return logits


def _sample(logits, rng, temperature: float, top_k: int,
            top_p: float = 0.0):
    """logits (B, V) f32 -> token ids (B,). temperature 0 = greedy;
    top_k and top_p (nucleus) filters compose (k first, then p)."""
    if temperature == 0.0:
        return jnp.argmax(logits, axis=-1).astype(jnp.int32)
    logits = filter_logits(logits / temperature, top_k, top_p)
    return jax.random.categorical(rng, logits, axis=-1).astype(jnp.int32)


# ------------------------------------------------- decode HBM roofline
#
# BASELINE.md established (round 4) that decode is HBM-bandwidth-bound
# at ~800 GB/s on this chip: every step sweeps the KV cache and the
# weights once. These helpers surface that as a LIVE number on decode
# progress lines (tokens/sec x bytes/token vs the chip's HBM roofline,
# `flops.device_mem_bandwidth`) instead of an offline claim. The byte
# model is pinned against the traced decode program's own input-buffer
# bytes (analysis/walker.aval_bytes) in tests/test_generate.py.


def decode_read_bytes_per_token(params, cfg: T.TransformerConfig,
                                batch: int, cache_len: int,
                                kv_quant: str = "") -> int:
    """HBM READ bytes one decode step moves: every param leaf (at the
    dtype decode actually reads after `cast_params`) plus every
    block's full K/V cache sweep (+ int8 scale rows), plus the token
    ids. Equals the summed input-buffer bytes of the traced
    `decode_step` program by construction — the walker pin."""
    import numpy as np

    from shallowspeed_tpu.analysis.walker import aval_bytes

    # eval_shape: the byte count needs only the casted avals, not a
    # full on-device copy of the model in compute dtype
    cast = jax.eval_shape(lambda p: T.cast_params(p, cfg.compute_dtype),
                          params)
    p_bytes = int(sum(aval_bytes(l) for l in
                      jax.tree_util.tree_leaves(cast)))
    kv_itemsize = (1 if kv_quant == "int8"
                   else np.dtype(cfg.compute_dtype or cfg.dtype).itemsize)
    per_block = 2 * batch * cfg.kv_heads * cache_len * cfg.head_dim \
        * kv_itemsize
    if kv_quant == "int8":
        per_block += 2 * batch * cfg.kv_heads * cache_len * 4  # f32 scales
    tok_bytes = batch * 4  # int32 token ids
    return p_bytes + cfg.n_layers * per_block + tok_bytes


def decode_write_bytes_per_token(cfg: T.TransformerConfig, batch: int,
                                 kv_quant: str = "") -> int:
    """HBM WRITE bytes per decode step: the one-token K/V cache update
    per block (+ scales) and the logits row — O(1/cache_len) of the
    read sweep, reported for completeness."""
    import numpy as np

    kv_itemsize = (1 if kv_quant == "int8"
                   else np.dtype(cfg.compute_dtype or cfg.dtype).itemsize)
    per_block = 2 * batch * cfg.kv_heads * cfg.head_dim * kv_itemsize
    if kv_quant == "int8":
        per_block += 2 * batch * cfg.kv_heads * 4
    return cfg.n_layers * per_block + batch * cfg.vocab * 4


def decode_report(params, cfg: T.TransformerConfig, batch: int,
                  cache_len: int, n_tokens: int, seconds: float,
                  kv_quant: str = "") -> dict:
    """Decode progress-line fields for a timed generation: tokens/sec,
    the analytic bytes/token, the implied HBM sweep rate, and — when
    the chip's HBM peak is known — the roofline utilization. Off-TPU
    `hbm_util` is None (no invented peak), matching flops.mfu's
    convention."""
    from shallowspeed_tpu.flops import device_mem_bandwidth

    if seconds <= 0 or n_tokens <= 0:
        # typed, not an assert (asserts vanish under python -O and this
        # guards a division on a production progress line)
        raise ValueError(f"decode_report needs seconds > 0 and "
                         f"n_tokens > 0, got seconds={seconds!r}, "
                         f"n_tokens={n_tokens!r}")
    steps_per_sec = n_tokens / seconds          # decode steps (all rows)
    bpt = (decode_read_bytes_per_token(params, cfg, batch, cache_len,
                                       kv_quant)
           + decode_write_bytes_per_token(cfg, batch, kv_quant))
    gbps = steps_per_sec * bpt / 1e9
    peak = device_mem_bandwidth()
    return {
        "tokens_per_sec": round(steps_per_sec * batch, 1),
        "steps_per_sec": round(steps_per_sec, 2),
        "bytes_per_token": int(bpt),
        "hbm_gbps": round(gbps, 4),
        "hbm_peak_gbps": None if peak is None else round(peak / 1e9, 1),
        "hbm_util": None if peak is None else round(gbps * 1e9 / peak,
                                                    4),
    }


FLASH_PREFILL_THRESHOLD = 2048
"""Prompt-BUCKET length at which `generate` switches the prefill from
XLA attention to the flash kernel (long prompts OOM on the (B, H, Tp,
Tp) f32 score materialization). Flash numerics differ at the ~1e-6
level, so sampled token streams across the switch are NOT bit-identical
— callers who need cross-length stream stability pin
`flash_prefill_at` in `generate` instead of relying on the default."""


@partial(jax.jit, static_argnames=("cfg", "max_new", "temperature",
                                   "top_k", "top_p", "cache_len",
                                   "kv_quant", "flash_prefill_at"))
def _generate_padded(params, prompt, tp_actual, cfg: T.TransformerConfig,
                     max_new: int, temperature: float, top_k: int,
                     top_p: float, seed, cache_len: int,
                     kv_quant: str = "",
                     flash_prefill_at: int = FLASH_PREFILL_THRESHOLD):
    """The compiled generation core on a BUCKET-padded prompt (B, Tp_b):
    `tp_actual` is the TRACED true prompt length, so every prompt in the
    same (Tp_b, max_new, sampler) bucket reuses one executable. The KV
    cache is `cache_len` = Tp_b + max_new slots — sized to the
    generation, not cfg.max_seq. One program: parallel prefill + a
    `lax.scan` decode loop over the static step count."""
    b = prompt.shape[0]
    params = T.cast_params(params, cfg.compute_dtype)  # once, not per step
    cache = init_kv_cache(cfg, b, cache_len, kv_quant)
    # long prompts stream the prefill through the flash kernel (the
    # XLA path materializes (B, H, Tp, Tp) f32 scores); prompts that
    # bucket BELOW the threshold keep the XLA path, so their streams
    # stay bit-identical to earlier rounds. Guard the tile size too: a
    # non-power-of-two length shrinks the Pallas block toward 1 (a
    # silent performance cliff worse than the OOM it avoids).
    from shallowspeed_tpu.ops.flash_attention import _pick_block

    attn_impl = ("flash" if flash_prefill_at > 0
                 and prompt.shape[1] >= flash_prefill_at
                 and _pick_block(prompt.shape[1], 512) >= 128
                 else "xla")
    logits, cache = prefill(params, prompt, cfg, cache,
                            last_idx=tp_actual - 1,
                            attn_impl=attn_impl)
    rng0 = jax.random.PRNGKey(seed)
    tok0 = _sample(logits, jax.random.fold_in(rng0, 0), temperature,
                   top_k, top_p)

    # sample-after-decode: the final sampled token never triggers another
    # (discarded) decode pass — exactly max_new - 1 decode steps run.
    # Decode position tp_actual + i OVERWRITES its (pad-garbage) cache
    # slot before the position mask can admit it (see prefill).
    def step(carry, i):
        tok_prev, cache = carry
        logits, cache = decode_step(params, tok_prev, tp_actual + i,
                                    cache, cfg)
        tok = _sample(logits, jax.random.fold_in(rng0, i + 1),
                      temperature, top_k, top_p)
        return (tok, cache), tok

    (_, _), toks = jax.lax.scan(step, (tok0, cache),
                                jnp.arange(max_new - 1))
    return jnp.concatenate([tok0[None], toks], axis=0).T  # (B, max_new)


def prompt_bucket_len(tp: int, max_new: int, max_seq: int,
                      bucket: int = 64) -> int:
    """Round the prompt length up to a `bucket` multiple (capped so the
    bucket + generation still fit max_seq) — the compile key for
    `generate`, shared with the pipelined decode."""
    tp_b = ((tp + bucket - 1) // bucket) * bucket
    return max(tp, min(tp_b, max_seq - max_new))


def generate(params, prompt, cfg: T.TransformerConfig, max_new: int,
             temperature: float = 1.0, top_k: int = 0,
             top_p: float = 0.0, seed=0, kv_quant: str = "",
             flash_prefill_at: int = FLASH_PREFILL_THRESHOLD):
    """Generate `max_new` tokens after `prompt` (B, Tp). Returns
    (B, max_new) int32.

    Compile hygiene (round 4, VERDICT r3): the prompt is right-padded
    to a 64-token bucket and its true length is passed traced, so
    same-bucket prompts of different lengths share ONE executable
    (previously every Tp recompiled); the KV cache holds
    bucket + max_new slots, not max_seq. Token streams are identical
    to the unpadded form — the pad slots are overwritten before the
    position mask can admit them.

    **Stream-stability contract.** For a fixed (seed, sampler, weights)
    the token stream is reproducible across runs and prompt paddings,
    with two documented exceptions: (1) prompts whose 64-token BUCKET
    reaches `flash_prefill_at` (default 2048) prefill through the flash
    kernel, whose numerics differ from XLA attention at the ~1e-6
    logit level — so streams are bit-stable WITHIN each regime but not
    across the switch. Callers needing one numerics regime for every
    length pin it: `flash_prefill_at=0` disables the auto-switch (XLA
    everywhere — long prompts then pay the (B, H, Tp, Tp) f32 score
    materialization), any other value moves the boundary. (2)
    `kv_quant="int8"` (round 5): quantized KV cache — halves the
    cache-sweep bytes for batched long-context decode at a small
    numerics cost (per-head absmax scales; logits move at the ~1e-2
    level, so streams are NOT bit-identical to the bf16 cache). (3)
    PAGED decode (round 11, `shallowspeed_tpu/serving/`): the serving
    engine reads the same cache math through a gathered block table
    (`models/kv_cache.masked_attention` is the shared core) with the
    same per-request sampling keys (`fold_in(PRNGKey(seed),
    token_index)`) — but its table width is bucketed in BLOCKS, not
    this path's 64-token prompt bucket, so the softmax reduction
    shape differs and paged logits match this path to ~1e-6 (pinned
    <= 1e-4), NOT bit-exactly. In practice sampled streams coincide
    (tests/test_serving.py pins solo-request streams token-for-token
    against this function, greedy and sampled); callers needing a
    guaranteed-bit-stable stream must stay on ONE of the two paths."""
    b, tp = prompt.shape
    assert tp + max_new <= cfg.max_seq, (
        f"prompt {tp} + max_new {max_new} exceeds max_seq={cfg.max_seq}")
    # jnp.asarray on BOTH branches (round 11): the no-padding branch
    # used to hand the caller's raw array straight to jit while the
    # padded branch converted — dtype/device normalization differed by
    # prompt LENGTH (e.g. int64 host arrays weak-typing differently),
    # a shape-dependent input regime
    prompt = jnp.asarray(prompt)
    tp_b = prompt_bucket_len(tp, max_new, cfg.max_seq)
    if tp_b != tp:
        prompt = jnp.pad(prompt, ((0, 0), (0, tp_b - tp)))
    return _generate_padded(params, prompt, jnp.int32(tp), cfg, max_new,
                            temperature, top_k, top_p, seed,
                            cache_len=tp_b + max_new,
                            kv_quant=kv_quant,
                            flash_prefill_at=flash_prefill_at)
