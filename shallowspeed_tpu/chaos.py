"""Deterministic fault injection — the adversarial proof of the
recovery stack.

The reference has no fault tolerance at all (SURVEY §5: any rank
failure kills the mpirun job), and our answer — the elastic supervisor,
health-guarded steps, atomic checkpoints, the goodput ledger — is only
trustworthy if something actually tries to break it. This module
schedules faults at *named injection points* wired into the drivers and
the checkpoint writer, so a chaos drill is a seeded, replayable plan
rather than a hand-run `kill -9`:

    python -m shallowspeed_tpu.elastic --max-restarts 4 \
        --chaos 'kill@9,corrupt@2,stall@5:0.5' --chaos-state ck/.chaos \
        -- python train_lm.py --save-dir ck --auto-resume ...

Fault kinds (`kind@at[:arg]`, comma-separated; `at` is a 0-based step
for step faults and a 1-based save ordinal for save faults):

- ``kill@N``          SIGKILL this process before dispatching step N —
                      the plain preemption/crash fault.
- ``kill_in_save@K``  SIGKILL *inside* the K-th checkpoint save's
                      tmp-write/rename window, at a seeded offset
                      (between file writes, pre-rename, or post-rename;
                      fires on the async saver's writer thread too) —
                      the save-atomicity fault.
- ``nan@N`` / ``inf@N``  poison one seeded parameter leaf before step N
                      so every subsequent gradient is non-finite — the
                      numerically-dead fault the health monitor must
                      escalate to the supervisor.
- ``stall@N:S``       sleep S seconds (default 2.0) in the data loader
                      at step N — must land in the ledger as
                      ``data_stall``, not vanish into the step rate.
- ``freeze@N``        stop writing heartbeats from step N on (the run
                      keeps stepping) — the hang fault only the
                      supervisor's staleness clock can catch.
- ``enospc@K``        the K-th save raises OSError(ENOSPC) mid-write —
                      atomicity means `latest()` must be unaffected.
- ``corrupt@K[:mode]``  after the K-th save lands, corrupt it post-hoc:
                      ``bitflip`` (default, one seeded bit in a seeded
                      npz), ``truncate`` (cut the npz in half), or
                      ``delete`` (unlink one member file) — the
                      manifest-verification fault.

Determinism and once-only semantics: the plan is seeded (`seed` picks
the poisoned leaf, the flipped bit, the kill offset inside a save) and
every fault fires AT MOST ONCE per plan — a fired fault stamps a marker
file into ``state_dir``, which must survive supervisor restarts (the
drivers default it to ``<save_dir>/.chaos``), so a restarted child
replays the fault window *clean*. That is what makes the acceptance
bar checkable: a supervised run under a multi-fault plan must finish
all steps with the exact loss trajectory of a fault-free oracle.

Propagation: the elastic supervisor exports the plan to its children
via ``SHALLOWSPEED_CHAOS`` / ``SHALLOWSPEED_CHAOS_STATE`` /
``SHALLOWSPEED_CHAOS_SEED``; the drivers' ``--chaos`` flag wins over
the environment. Every fired fault is stamped as a schema-v5
``{"event": "fault", ...}`` line into the run's metrics JSONL
(fsync'd — the process may be about to die), so the forensic record of
*what was injected when* lives next to the step lines it perturbed.
"""

from __future__ import annotations

import errno
import json
import os
import signal
import time
from dataclasses import dataclass, field
from pathlib import Path

import numpy as np

# env vars the elastic supervisor exports so a restarted child keeps
# executing the same plan (with the same fired-marker state)
ENV_SPEC = "SHALLOWSPEED_CHAOS"
ENV_STATE = "SHALLOWSPEED_CHAOS_STATE"
ENV_SEED = "SHALLOWSPEED_CHAOS_SEED"

STEP_KINDS = ("kill", "nan", "inf", "stall", "freeze",
              "scale_poison")
SAVE_KINDS = ("kill_in_save", "enospc", "corrupt")
KINDS = STEP_KINDS + SAVE_KINDS

_CORRUPT_MODES = ("bitflip", "truncate", "delete")


@dataclass(frozen=True)
class Fault:
    """One scheduled fault: `kind` at step/save-ordinal `at`, with an
    optional kind-specific `arg` (stall seconds, corrupt mode)."""

    kind: str
    at: int
    arg: str | float | None = None

    def __post_init__(self):
        if self.kind not in KINDS:
            raise ValueError(f"unknown fault kind {self.kind!r} "
                             f"(know {', '.join(KINDS)})")
        if self.kind in SAVE_KINDS and self.at < 1:
            raise ValueError(f"{self.kind} takes a 1-based save "
                             f"ordinal, got {self.at}")
        if self.kind == "corrupt" and self.arg is not None \
                and self.arg not in _CORRUPT_MODES:
            raise ValueError(f"corrupt mode {self.arg!r} not in "
                             f"{_CORRUPT_MODES}")

    @property
    def id(self) -> str:
        """Stable token — doubles as the fired-marker filename stem."""
        tail = "" if self.arg is None else f":{self.arg}"
        return f"{self.kind}@{self.at}{tail}"


def _parse_token(tok: str) -> Fault:
    if "@" not in tok:
        raise ValueError(
            f"bad fault token {tok!r} (want kind@at[:arg], e.g. "
            f"'kill@9' or 'stall@5:2.5')")
    kind, _, rest = tok.partition("@")
    at, _, arg = rest.partition(":")
    parsed: str | float | None = None
    if arg:
        if kind == "corrupt":
            parsed = arg
        else:
            parsed = float(arg)
    try:
        at_i = int(at)
    except ValueError:
        raise ValueError(f"bad fault position in {tok!r}: {at!r} is "
                         f"not an integer step/save ordinal") from None
    return Fault(kind.strip(), at_i, parsed)


class FaultPlan:
    """A seeded schedule of faults plus the once-only firing state.

    `state_dir=None` keeps fired markers in-process only — fine for a
    single-process drill, wrong under a supervisor (the restarted child
    would re-fire every fault); the drivers default the state dir to
    ``<save_dir>/.chaos`` so the markers survive restarts.
    """

    def __init__(self, faults: list[Fault], seed: int = 0,
                 state_dir=None, log_file=None):
        self.faults = list(faults)
        self.seed = int(seed)
        self.state_dir = Path(state_dir) if state_dir else None
        if self.state_dir is not None:
            self.state_dir.mkdir(parents=True, exist_ok=True)
        self.log_file = str(log_file) if log_file else None
        self._mem_fired: set[str] = set()
        self._mem_saves = 0        # save ordinal when state_dir is None
        self._frozen = False       # heartbeat freeze is in-process state
        # in-flight save bookkeeping (kill_in_save): set at save start
        self._save_target: int | None = None
        self._save_point = 0
        self._save_fault: Fault | None = None

    # ------------------------------------------------------- parse/spec

    @classmethod
    def parse(cls, spec: str, seed: int = 0, state_dir=None,
              log_file=None) -> "FaultPlan":
        """Parse the compact DSL, inline JSON, or a path to a JSON
        plan file (``{"seed": 0, "faults": [{"kind", "at", "arg"}]}``)."""
        spec = spec.strip()
        if spec.startswith("{"):
            return cls._from_json(json.loads(spec), seed, state_dir,
                                  log_file)
        if spec.endswith(".json") and Path(spec).exists():
            return cls._from_json(json.loads(Path(spec).read_text()),
                                  seed, state_dir, log_file)
        faults = [_parse_token(t) for t in spec.split(",") if t.strip()]
        if not faults:
            raise ValueError(f"empty chaos spec {spec!r}")
        return cls(faults, seed=seed, state_dir=state_dir,
                   log_file=log_file)

    @classmethod
    def _from_json(cls, obj: dict, seed, state_dir, log_file):
        faults = [Fault(f["kind"], int(f["at"]), f.get("arg"))
                  for f in obj.get("faults", ())]
        if not faults:
            raise ValueError("chaos JSON plan has no faults")
        return cls(faults, seed=int(obj.get("seed", seed)),
                   state_dir=state_dir, log_file=log_file)

    def to_spec(self) -> str:
        """The compact DSL round-trip (what export_env propagates)."""
        return ",".join(f.id for f in self.faults)

    def export_env(self, env: dict | None = None) -> dict:
        """Child-process env carrying this plan (supervisor side)."""
        env = dict(os.environ if env is None else env)
        env[ENV_SPEC] = self.to_spec()
        env[ENV_SEED] = str(self.seed)
        if self.state_dir is not None:
            env[ENV_STATE] = str(self.state_dir)
        return env

    # -------------------------------------------------- firing/markers

    def _rng(self, fault: Fault) -> np.random.Generator:
        """Per-fault deterministic stream: the plan seed plus the
        fault's position in the plan."""
        return np.random.default_rng([self.seed,
                                      self.faults.index(fault)])

    def fired(self, fault: Fault) -> bool:
        if fault.id in self._mem_fired:
            return True
        if self.state_dir is not None:
            return (self.state_dir / self._marker(fault)).exists()
        return False

    def _marker(self, fault: Fault) -> str:
        safe = fault.id.replace("@", "_at_").replace(":", "_")
        return f"fired_{safe}"

    def _mark(self, fault: Fault) -> None:
        self._mem_fired.add(fault.id)
        if self.state_dir is not None:
            (self.state_dir / self._marker(fault)).write_text(
                f"{time.time():.3f}\n")

    def stamp(self, fault: Fault, **extra) -> None:
        """Append the schema-v5 fault event to the metrics JSONL,
        fsync'd — a kill fault dies microseconds later and the forensic
        record must already be durable. Best effort: injecting a fault
        must never crash the run in an unplanned way. Registered
        observers (`add_observer` — the live monitor's flight
        recorder) see the record too, BEFORE the stamp hits disk: a
        kill fault's flight dump must happen while the process still
        exists."""
        rec = {"event": "fault", "kind": fault.kind,
               "fault_id": fault.id, "wall": round(time.time(), 3),
               **extra}
        for fn in list(_observers):
            try:
                fn(rec)
            except Exception:
                pass
        if self.log_file is None:
            return
        try:
            with open(self.log_file, "a") as f:
                f.write(json.dumps(rec) + "\n")
                f.flush()
                os.fsync(f.fileno())
        except OSError:
            pass

    def _fire(self, fault: Fault, **extra) -> None:
        """Marker first, stamp second: even a SIGKILL microseconds into
        the fault body must not let a restarted child re-fire it."""
        self._mark(fault)
        self.stamp(fault, **extra)

    # ------------------------------------------------- step-loop hooks

    def due(self, kind: str, at: int) -> Fault | None:
        for f in self.faults:
            if f.kind == kind and f.at == at and not self.fired(f):
                return f
        return None

    def on_step(self, step: int, engine=None) -> None:
        """Driver hook, top of the step loop. Order matters: freeze and
        poison first (they leave the process alive), kill last."""
        f = self.due("freeze", step)
        if f is not None:
            self._fire(f, step=step)
            self._frozen = True
        for kind in ("nan", "inf"):
            f = self.due(kind, step)
            if f is not None:
                if engine is None:
                    raise RuntimeError(
                        f"chaos fault {f.id} needs an engine to poison")
                leaf = self._poison(engine, f, kind)
                self._fire(f, step=step, leaf=leaf)
        f = self.due("scale_poison", step)
        if f is not None:
            if engine is None:
                raise RuntimeError(
                    f"chaos fault {f.id} needs an engine to poison")
            layer = self._poison_scale(engine, f)
            self._fire(f, step=step, layer=layer)
        f = self.due("kill", step)
        if f is not None:
            self._fire(f, step=step)
            os.kill(os.getpid(), signal.SIGKILL)

    def _poison(self, engine, fault: Fault, kind: str) -> int:
        """Multiply one seeded param leaf by NaN/Inf: every gradient
        that touches it goes non-finite next step — the storm the
        health monitor must escalate. Whole-leaf scaling keeps the
        leaf's sharding/placement untouched."""
        import jax

        leaves, treedef = jax.tree_util.tree_flatten(engine.params)
        idx = int(self._rng(fault).integers(0, len(leaves)))
        bad = float("nan") if kind == "nan" else float("inf")
        leaves[idx] = leaves[idx] * bad
        try:
            engine.params = jax.tree_util.tree_unflatten(treedef, leaves)
        except AttributeError:
            raise RuntimeError(
                f"chaos fault {fault.id} needs an engine with "
                f"assignable params; {type(engine).__name__} exposes "
                f"a read-only view (use kill/stall/freeze/save faults "
                f"with this engine)") from None
        return idx

    def _poison_scale(self, engine, fault: Fault) -> int:
        """Zero one seeded layer's fp8 amax history: its delayed scale
        collapses to the 1e-12 divide floor next step, so every
        quantize on that layer saturates — the numerics-observatory
        failure mode (scale_collapse verdict + shadow-parity blowup)
        rather than the nan/inf gradient storm. The params are
        untouched; only the scaling STATE is corrupted, which is
        exactly what a lost/corrupt amax sync looks like in the wild."""
        hist = getattr(engine, "amax_hist", None)
        if hist is None:
            raise RuntimeError(
                f"chaos fault {fault.id} needs an engine with an "
                f"amax_hist (fp8 delayed scaling); "
                f"{type(engine).__name__} has none — use "
                f"kill/nan/inf/stall/freeze with this engine")
        layer = int(self._rng(fault).integers(0, hist.shape[0]))
        engine.amax_hist = hist.at[layer].set(0.0)
        return layer

    def heartbeat_frozen(self) -> bool:
        return self._frozen

    def unfired(self) -> list[str]:
        """Faults still scheduled but never fired — a drill that ends
        with entries here injected LESS than planned (e.g. a save
        fault's ordinal was consumed by a killed attempt, or a step
        fault's step fell inside a replayed-from-checkpoint window the
        marker already covered). The drivers report this at clean exit
        so a green drill can't silently under-inject."""
        return [f.id for f in self.faults if not self.fired(f)]

    def on_data_load(self, step: int) -> None:
        """Data-loader hook (the drivers' batch producers and
        data/dataset.py): a stall fault sleeps here, and the seconds
        must surface as ledger `data_stall`, not disappear."""
        f = self.due("stall", step)
        if f is not None:
            secs = float(f.arg) if f.arg is not None else 2.0
            self._fire(f, step=step, seconds=round(secs, 3))
            time.sleep(secs)

    # ------------------------------------------------------ save hooks

    def _save_count(self, advance: bool) -> int:
        """1-based ordinal of the current save, shared across restarts
        through the state dir (a fault aimed at save K must count the
        saves earlier children already completed)."""
        if self.state_dir is None:
            if advance:
                self._mem_saves += 1
            return self._mem_saves
        p = self.state_dir / "save_count"
        try:
            n = int(p.read_text())
        except (OSError, ValueError):
            n = 0
        if advance:
            n += 1
            p.write_text(str(n))
        return n

    def on_save(self, point: str) -> None:
        """Checkpoint-writer hook (`checkpoint._write_ckpt`). Points
        stream in as ``start``, ``file:<name>`` per npz written,
        ``pre_rename``, ``renamed``. ENOSPC raises at the first write
        point; kill_in_save SIGKILLs at a seeded point offset."""
        if point == "start":
            n = self._save_count(advance=True)
            self._save_point = 0
            self._save_target = None
            self._save_fault = None
            f = self.due("kill_in_save", n)
            if f is not None:
                # seeded offset among the upcoming points; 6 exceeds
                # any real save's point count, so high draws fall
                # through to fire at the 'renamed' point
                self._save_target = int(self._rng(f).integers(0, 6))
                self._save_fault = f
            f = self.due("enospc", n)
            if f is not None:
                self._fire(f, save=n)
                raise OSError(errno.ENOSPC, os.strerror(errno.ENOSPC),
                              "chaos: injected ENOSPC during save")
            return
        if self._save_fault is None:
            return
        hit = (self._save_point == self._save_target
               or point == "renamed")
        self._save_point += 1
        if hit:
            f, self._save_fault = self._save_fault, None
            self._fire(f, point=point)
            os.kill(os.getpid(), signal.SIGKILL)

    def after_save(self, final_path) -> None:
        """Post-hoc corruption of a just-landed checkpoint: a seeded
        bit flip / truncation / member deletion the manifest
        verification must catch at the next restore."""
        n = self._save_count(advance=False)
        f = self.due("corrupt", n)
        if f is None:
            return
        mode = f.arg or "bitflip"
        rng = self._rng(f)
        npz = sorted(Path(final_path).glob("*.npz"))
        if not npz:
            return
        target = npz[int(rng.integers(0, len(npz)))]
        if mode == "delete":
            target.unlink()
        elif mode == "truncate":
            data = target.read_bytes()
            target.write_bytes(data[: max(1, len(data) // 2)])
        else:  # bitflip
            data = bytearray(target.read_bytes())
            pos = int(rng.integers(0, len(data)))
            data[pos] ^= 1 << int(rng.integers(0, 8))
            target.write_bytes(bytes(data))
        self._fire(f, save=n, path=str(target), mode=mode)


def parse_fleet_spec(spec: str) -> dict:
    """Per-replica chaos plans for fleet drills: the router driver's
    ``--chaos-fleet 'r0=kill@6;r1=stall@3:0.5'`` maps replica NAMES to
    ordinary plan specs — a drill targets one member of a fleet, not
    every process that happens to share the environment. Each
    sub-spec is validated eagerly (fail at arg time, not when the
    replica finally spawns); returns {name: spec}."""
    out: dict[str, str] = {}
    for tok in spec.split(";"):
        tok = tok.strip()
        if not tok:
            continue
        name, eq, sub = tok.partition("=")
        if not eq or not name.strip() or not sub.strip():
            raise ValueError(
                f"bad fleet chaos token {tok!r} (want "
                f"'replica=plan', e.g. 'r0=kill@6;r1=stall@3:0.5')")
        for t in sub.split(","):
            if t.strip():
                _parse_token(t)       # typed error on a bad sub-plan
        out[name.strip()] = sub.strip()
    if not out:
        raise ValueError(f"empty fleet chaos spec {spec!r}")
    return out


# --------------------------------------------------- module-level plan
#
# One plan per process: the drivers configure it from --chaos (or the
# supervisor-exported env), and the checkpoint writer's hooks read it
# through active() — including on the async saver's writer thread,
# which shares this module state.

_PLAN: FaultPlan | None = None
_ENV_CHECKED = False

# fault-stamp observers (round 12): the live monitor registers its
# `note_line` here so an injected fault reaches the flight recorder
# IN-PROCESS, before the process the fault may be about to kill is
# gone — the JSONL tail alone would only serve post-mortem tailers.
_observers: list = []


def add_observer(fn) -> None:
    """Register a callable(record_dict) invoked at every fault stamp."""
    if fn not in _observers:
        _observers.append(fn)


def remove_observer(fn) -> None:
    try:
        _observers.remove(fn)
    except ValueError:
        pass


def configure(plan: FaultPlan | None) -> FaultPlan | None:
    global _PLAN, _ENV_CHECKED
    _PLAN = plan
    _ENV_CHECKED = True
    return plan


def setup(spec: str = "", seed: int = 0, state_dir=None,
          log_file=None) -> FaultPlan | None:
    """Driver entry: install a plan from the --chaos flag, falling back
    to the supervisor-exported environment. Returns None (and installs
    nothing) when neither names a plan."""
    env_seed = os.environ.get(ENV_SEED)
    if not spec:
        spec = os.environ.get(ENV_SPEC, "")
        if not spec:
            return configure(None)
        if env_seed is not None:
            seed = int(env_seed)
        # the plan came from the supervisor: its exported state dir is
        # the operator's --chaos-state and must win over the driver's
        # derived <save-dir>/.chaos default, or clearing the operator's
        # dir to rerun a drill would silently change nothing
        state_dir = os.environ.get(ENV_STATE) or state_dir
    return configure(FaultPlan.parse(spec, seed=seed,
                                     state_dir=state_dir,
                                     log_file=log_file))


def active() -> FaultPlan | None:
    """The installed plan — lazily adopted from the environment so the
    checkpoint hooks fire even in a process that never called setup()
    (e.g. a bare `checkpoint.save` under a supervisor-exported plan)."""
    global _ENV_CHECKED
    if _PLAN is None and not _ENV_CHECKED:
        _ENV_CHECKED = True
        if os.environ.get(ENV_SPEC):
            return setup()
    return _PLAN


# thin no-op-when-inactive wrappers for call sites that should not
# care whether a plan is installed

def on_step(step: int, engine=None) -> None:
    plan = active()
    if plan is not None:
        plan.on_step(step, engine)


def on_data_load(step: int) -> None:
    plan = active()
    if plan is not None:
        plan.on_data_load(step)


def on_save(point: str) -> None:
    plan = active()
    if plan is not None:
        plan.on_save(point)


def after_save(final_path) -> None:
    plan = active()
    if plan is not None:
        plan.after_save(final_path)


def heartbeat_frozen() -> bool:
    plan = active()
    return plan is not None and plan.heartbeat_frozen()
