"""Model-FLOPs accounting and MFU (model FLOPs utilization).

The reference has no notion of utilization — its "perf story" is
wall-clock epoch prints (`/root/reference/train.py:131-137`). On TPU the
bar is fraction-of-peak: the MXU has a fixed bf16 throughput per chip, so
achieved-TFLOP/s divided by that peak is the hardware-honest headline.

FLOPs are counted exactly from the model config — every matmul's 2*M*N*K,
not the 6N approximation — and follow the standard *model* FLOPs
convention (PaLM appendix B): forward + 2x backward = 3x forward, counting
only algorithmically required work. Rematerialization's extra forward is
deliberately NOT counted (that is what makes this MFU, not HFU).
"""

from __future__ import annotations

# Peak dense matmul throughput per JAX DEVICE, FLOP/s (bf16, published
# spec sheets). The unit is deliberately the device, not the chip: on
# v2/v3 JAX exposes each TensorCore as a separate device (2 per chip —
# `jax.local_devices()` on a v3-8 host lists 8 devices on 4 chips), so
# their entries are the per-core half of the chip spec (v2: 45/2, v3:
# 123/2). From v4 on the two cores are fused (megacore) and device ==
# chip, so those entries are chip peaks. This is what makes
# `mfu(n_devices=mesh size)` correct on every generation: mesh axes
# count devices, and the table is per-device. f32 is derived below as the
# measured-practical MXU f32 ratio (~1/8 of bf16 via multi-pass
# emulation on v4/v5 generations).
_PEAKS_BF16 = {
    "TPU v2": 22.5e12,   # per core; chip spec 45 TFLOP/s, 2 cores/chip
    "TPU v3": 61.5e12,   # per core; chip spec 123 TFLOP/s, 2 cores/chip
    "TPU v4": 275e12,
    "TPU v5 lite": 197e12,
    "TPU v5e": 197e12,
    "TPU v5": 459e12,    # v5p
    "TPU v5p": 459e12,
    "TPU v6 lite": 918e12,  # Trillium / v6e
    "TPU v6e": 918e12,
    "TPU v7": 2307e12,   # Ironwood (per-chip, dense fp8 4.6PF -> bf16 2.3)
}


# HBM bandwidth per JAX DEVICE, bytes/s (published spec sheets; same
# device-vs-chip convention as _PEAKS_BF16 — v2/v3 entries are the
# per-core half of the shared chip HBM). The v5e entry matches the
# 819 GB/s this repo's own decode sweeps measured at the roofline
# (BASELINE.md "flash-decode kernel evaluation").
_HBM_BPS = {
    "TPU v2": 350e9,     # 700 GB/s chip, 2 cores
    "TPU v3": 450e9,     # 900 GB/s chip, 2 cores
    "TPU v4": 1228e9,
    "TPU v5 lite": 819e9,
    "TPU v5e": 819e9,
    "TPU v5": 2765e9,    # v5p
    "TPU v5p": 2765e9,
    "TPU v6 lite": 1640e9,
    "TPU v6e": 1640e9,
    "TPU v7": 7370e9,
}

# Aggregate inter-chip interconnect bandwidth per JAX device, bytes/s,
# one direction (approximate — published aggregate link rates; the
# attribution waterfall needs order-of-magnitude wire time, not a
# topology model, and the per-primitive algorithm factors are
# deliberately left to the reader like collectives.py's byte counts).
_ICI_BPS = {
    "TPU v2": 60e9,
    "TPU v3": 100e9,
    "TPU v4": 300e9,     # 2400 Gbps
    "TPU v5 lite": 200e9,  # 1600 Gbps
    "TPU v5e": 200e9,
    "TPU v5": 600e9,     # v5p, 4800 Gbps
    "TPU v5p": 600e9,
    "TPU v6 lite": 448e9,
    "TPU v6e": 448e9,
    "TPU v7": 1200e9,
}


def _lookup_kind(device, table) -> float | None:
    """Longest-prefix match of `device`'s kind against a peaks table
    ("TPU v5 lite" beats "TPU v5"); None when unknown (CPU meshes)."""
    import jax

    if device is None:
        devs = jax.devices()
        if not devs:
            return None
        device = devs[0]
    kind = getattr(device, "device_kind", "")
    best = None
    for name, val in table.items():
        if kind.startswith(name):
            if best is None or len(name) > best[0]:
                best = (len(name), val)
    return None if best is None else best[1]


def device_peak_flops(device=None, dtype: str = "bf16") -> float | None:
    """Peak FLOP/s of one JAX device of `device`'s kind (default:
    jax.devices()[0]). "Device" is a whole chip on v4+ and a single
    TensorCore on v2/v3 (see _PEAKS_BF16) — the right denominator for
    per-device throughput either way.

    Returns None when the device kind is unknown (CPU test meshes) —
    callers should then skip MFU reporting rather than invent a peak.
    """
    p = _lookup_kind(device, _PEAKS_BF16)
    if p is None:
        return None
    if dtype in ("f32", "float32", "fp32"):
        return p / 8.0  # multi-pass MXU emulation; measured-practical
    if dtype in ("fp8", "float8", "e4m3", "float8_e4m3fn"):
        # dense fp8 runs the MXU at 2x its bf16 rate on generations
        # that support it natively (see the v7 entry's 4.6PF -> 2.3
        # note); the same 2x is what telemetry/attribution prices
        # fp8-operand dot FLOPs at when building the roofline
        return p * 2.0
    return p


def device_mem_bandwidth(device=None) -> float | None:
    """Peak HBM bytes/s of one JAX device (None off-TPU) — the
    denominator for memory-roofline utilization (decode sweeps,
    telemetry/attribution's fusion pricing)."""
    return _lookup_kind(device, _HBM_BPS)


def device_ici_bandwidth(device=None) -> float | None:
    """Approximate aggregate ICI bytes/s of one JAX device (None
    off-TPU) — telemetry/attribution's exposed-collective wire rate."""
    return _lookup_kind(device, _ICI_BPS)


def _avg_causal_context(seq_len: int, window: int = 0) -> float:
    """Average number of visible key positions per query under causal
    masking, optionally with a sliding window of `window` positions."""
    t = seq_len
    if window and window < t:
        w = window
        # positions 0..w-1 see i+1 keys; positions w-1..t-1 see w keys
        return (w * (w + 1) / 2 + (t - w) * w) / t
    return (t + 1) / 2


def transformer_flops_per_token(cfg, seq_len: int,
                                include_backward: bool = True) -> float:
    """Exact matmul FLOPs per token for one train (fwd+bwd) or fwd step.

    Counts every projection, the FFN (dense gelu/swiglu or top-k MoE),
    the attention score/value matmuls (causal-averaged, window-aware),
    and the vocab head. Norms/softmax/rotary are vector ops — omitted,
    as is standard (they are HBM-bound, not MXU work).
    """
    d = cfg.d_model
    # one source of truth with transformer.init (ADVICE r2: a hardcoded
    # 4*d here would silently misreport MFU if d_ff ever diverges)
    ff = cfg.ffn_dim
    per_layer = 0.0
    # attention projections
    if cfg.gqa:
        per_layer += 2.0 * d * d                            # q proj
        per_layer += 2.0 * d * (2 * cfg.kv_heads * cfg.head_dim)  # kv
    else:
        per_layer += 2.0 * d * 3 * d                        # fused qkv
    per_layer += 2.0 * d * d                                # out proj
    # attention itself: QK^T and AV are each 2*head_dim*ctx per head
    ctx = _avg_causal_context(seq_len, getattr(cfg, "attn_window", 0))
    per_layer += 2 * (2.0 * cfg.n_heads * cfg.head_dim * ctx)
    # FFN
    if cfg.n_experts > 0:
        per_layer += 2.0 * d * cfg.n_experts                # router
        per_layer += cfg.moe_top_k * (2.0 * d * ff + 2.0 * ff * d)
    elif cfg.ffn == "swiglu":
        per_layer += 3 * 2.0 * d * ff                       # gate, up, down
    else:
        per_layer += 2 * 2.0 * d * ff                       # up, down
    total = cfg.n_layers * per_layer
    total += 2.0 * d * cfg.vocab                            # head logits
    if include_backward:
        total *= 3.0  # fwd + 2x bwd (PaLM appendix B convention)
    return total


def mfu(tokens_per_sec: float, cfg, seq_len: int,
        dtype: str = "bf16", device=None, n_devices: int | None = None,
        include_backward: bool = True, n_chips: int | None = None) -> dict:
    """Achieved TFLOP/s and fraction-of-peak for a measured throughput.

    `tokens_per_sec` is usually the GLOBAL rate; pass `n_devices` = the
    number of JAX devices producing it (the mesh size — on v2/v3 that
    counts TensorCores, matching the per-core table entries) so the
    denominator is the fleet peak, not one device's — otherwise a dp=4
    run reports 4x its true utilization. Returns {"tflops": achieved,
    "peak_tflops": fleet peak or None, "mfu": fraction or None}. MFU is
    None off-TPU (unknown peak)."""
    if n_chips is not None:  # deprecated pre-round-4 keyword
        import warnings

        warnings.warn("mfu(n_chips=...) is deprecated; pass n_devices",
                      DeprecationWarning, stacklevel=2)
        # None-sentinel default so an EXPLICIT n_devices=1 still
        # conflicts (1 being the old default must not mask it)
        if n_devices is not None and n_devices != n_chips:
            raise ValueError(
                f"both n_devices={n_devices} and n_chips={n_chips} "
                f"given and they disagree; pass only n_devices")
        n_devices = n_chips
    if n_devices is None:
        n_devices = 1
    fpt = transformer_flops_per_token(cfg, seq_len, include_backward)
    achieved = tokens_per_sec * fpt
    peak = device_peak_flops(device, dtype)
    if peak is not None:
        peak *= max(1, int(n_devices))
    return {
        "tflops": achieved / 1e12,
        "peak_tflops": None if peak is None else peak / 1e12,
        "mfu": None if peak is None else achieved / peak,
    }


# Back-compat alias (pre-round-4 name; the table was always per-device)
chip_peak_flops = device_peak_flops
