"""shallowspeed_tpu — a TPU-native (JAX/XLA) distributed-training framework.

Re-designs the capability set of juvi21/ShallowSpeed (reference at
/root/reference) for TPU hardware: jit-compiled jax.numpy ops with
hand-written VJPs, pure-functional stage-partitioned models, schedules as
testable pure data driving a pipeline VM, and SPMD parallelism over a 2-D
(dp, pp) `jax.sharding.Mesh` with XLA collectives (psum / ppermute) instead
of mpi4py Iallreduce / Send / Recv.
"""

__version__ = "0.1.0"

from shallowspeed_tpu.ops import functional  # noqa: F401
