"""shallowspeed_tpu — a TPU-native (JAX/XLA) distributed-training framework.

Re-designs the capability set of juvi21/ShallowSpeed (reference at
/root/reference) for TPU hardware: jit-compiled jax.numpy ops with
hand-written VJPs, pure-functional stage-partitioned models, schedules as
testable pure data driving a pipeline VM, and SPMD parallelism over
`jax.sharding.Mesh` axes (dp / pp / sp / tp / ep) with XLA collectives
(psum / ppermute / all_to_all) instead of mpi4py Iallreduce / Send / Recv.

Public API (lazily imported so `import shallowspeed_tpu` stays cheap):

    from shallowspeed_tpu import (
        FusedDPEngine, SPMDPipelineEngine, PipelineExecutor,      # MLP
        ContextParallelEngine, TensorParallelEngine,              # LM
        ExpertParallelEngine, FSDPEngine, Composite3DEngine,
        PipelineLMEngine,
        TransformerConfig, generate,
        SGD, MomentumSGD, Adam, AdamW, Adafactor, ema_update,
        OPTIMIZERS, SCHEDULES,
        ByteBPE, train_bpe, simulate_schedule,
        analysis, checkpoint, distributed, metrics,
    )
"""

__version__ = "0.1.0"

from shallowspeed_tpu.ops import functional  # noqa: F401

_EXPORTS = {
    # engines
    "FusedDPEngine": "shallowspeed_tpu.engine",
    "PipelineExecutor": "shallowspeed_tpu.parallel.worker",
    "SPMDPipelineEngine": "shallowspeed_tpu.parallel.spmd_pipeline",
    "ContextParallelEngine": "shallowspeed_tpu.parallel.context",
    "TensorParallelEngine": "shallowspeed_tpu.parallel.tensor",
    "ExpertParallelEngine": "shallowspeed_tpu.parallel.expert",
    "FSDPEngine": "shallowspeed_tpu.parallel.fsdp",
    "Composite3DEngine": "shallowspeed_tpu.parallel.composite",
    "PipelineLMEngine": "shallowspeed_tpu.parallel.pipeline_lm",
    # models
    "TransformerConfig": "shallowspeed_tpu.models.transformer",
    "MLPStage": "shallowspeed_tpu.models.mlp",
    "generate": "shallowspeed_tpu.models.generate",
    # optimizers
    "SGD": "shallowspeed_tpu.optim",
    "MomentumSGD": "shallowspeed_tpu.optim",
    "Adam": "shallowspeed_tpu.optim",
    "AdamW": "shallowspeed_tpu.optim",
    "Adafactor": "shallowspeed_tpu.optim",
    "ema_init": "shallowspeed_tpu.optim",
    "ema_update": "shallowspeed_tpu.optim",
    "OPTIMIZERS": "shallowspeed_tpu.optim",
    "SCHEDULES": "shallowspeed_tpu.optim",
    # data / tooling
    "ByteBPE": "shallowspeed_tpu.data.tokenizer",
    "train_bpe": "shallowspeed_tpu.data.tokenizer",
    "simulate_schedule": "shallowspeed_tpu.parallel.verify",
    # failure detection / elastic recovery
    "Supervisor": "shallowspeed_tpu.elastic",
    "RestartPolicy": "shallowspeed_tpu.elastic",
    # subsystem modules
    "ServingEngine": "shallowspeed_tpu.serving",
    "analysis": "shallowspeed_tpu.analysis",
    "chaos": "shallowspeed_tpu.chaos",
    "checkpoint": "shallowspeed_tpu.checkpoint",
    "distributed": "shallowspeed_tpu.distributed",
    "elastic": "shallowspeed_tpu.elastic",
    "metrics": "shallowspeed_tpu.metrics",
    "optim": "shallowspeed_tpu.optim",
    "serving": "shallowspeed_tpu.serving",
    "telemetry": "shallowspeed_tpu.telemetry",
    "utils": "shallowspeed_tpu.utils",
}

_MODULE_EXPORTS = {"analysis", "chaos", "checkpoint", "distributed",
                   "elastic", "metrics", "optim", "serving", "telemetry",
                   "utils"}

__all__ = sorted(_EXPORTS) + ["functional"]


def __getattr__(name):  # PEP 562 lazy re-exports
    target = _EXPORTS.get(name)
    if target is None:
        raise AttributeError(
            f"module {__name__!r} has no attribute {name!r}")
    import importlib

    mod = importlib.import_module(target)
    value = mod if name in _MODULE_EXPORTS else getattr(mod, name)
    globals()[name] = value  # cache for next access
    return value


def __dir__():
    return __all__
