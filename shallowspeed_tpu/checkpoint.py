"""Checkpoint / resume — save and restore training state.

The reference has no checkpointing (SURVEY §5: weights live only in memory,
`/root/reference/shallowspeed/layers.py:17-28`; the only serialization in its
repo is the PyTorch baseline's `torch.save`,
`scripts/DDP_PyTorch_MNIST.py:157-161`). This subsystem goes beyond parity:

- **Canonical format**: model parameters are stored engine-agnostically as
  the flat list of layer dicts `[{"W", "b"}, ...]` over the *whole* model
  (the pp=1 view). Every engine can export/import it, so a checkpoint
  written by a dp=4 fused run restores into a dp=2 x pp=4 SPMD run — the
  payoff of the reference's deterministic partitioning design
  (`layers.py:104-113`) carried over to serialized state.
- **Optimizer state** is engine-shaped in `opt.npz` (exact same-kind
  round trip) and ALSO available canonically (per-layer, unpadded,
  engine-agnostic like params): for identity-layout engines (the GSPMD
  family, context, fused DP) `opt.npz` already IS canonical (flagged
  `opt_is_canonical` in meta — no duplicate file, no second device
  fetch); layout-transforming engines (the pipeline) additionally write
  `opt_canon.npz` via `Optimizer.map_state_trees` + their params-layout
  transform. Cross-engine resume then restores moments exactly (a dp=4
  Adam checkpoint resumes into dp=2 x pp=4, and the MLP family's
  fused / padded-SPMD / per-stage-VM engines interchange moments the
  same way); only genuinely non-portable state (Adafactor's factored
  vectors across factoring-incompatible placements) falls back to
  re-initialization with a warning.
- On-disk format: one `.npz` per pytree — numbered array leaves plus a JSON
  structure descriptor. No pickle anywhere (a checkpoint from an untrusted
  source cannot execute code at load time), no orbax dependency, loadable
  with plain numpy.
- **Atomic AND durable**: `save` writes `ckpt_N.tmp/` and renames it into
  place, so a crash mid-save never leaves a directory that `latest()`
  would pick up; every npz (and the directories around the rename) is
  fsync'd, so a *host* crash after the rename cannot lose a checkpoint
  the caller was told is durable; `latest()` additionally ignores
  incomplete/foreign entries.
- **Integrity** (round 10): the atomic dir carries a per-file SHA-256
  `manifest.json`; `restore`/`latest()` verify it, raise a typed
  `CheckpointError` (never a raw `zipfile.BadZipFile`) on any load-path
  failure, quarantine a corrupt dir as `ckpt_N.corrupt`, and fall back
  to the newest *verified* checkpoint (`restore_latest`). Retention
  (`keep=`/`--keep-last`) never deletes the last verified checkpoint.
  Pre-manifest checkpoints stay restorable (verified by completeness
  only — there is nothing to hash against).
- `restore` validates the checkpoint's parameter structure and shapes
  against the engine before installing anything — a config-mismatched
  `--resume` is a hard error (`ValueError`, a user error distinct from
  corruption), not silent corruption.
"""

from __future__ import annotations

import hashlib
import json
import os
import re
import shutil
import warnings
from pathlib import Path

import jax
import numpy as np

tree_flatten = jax.tree_util.tree_flatten

_FILES = ("params.npz", "opt.npz")
_MANIFEST = "manifest.json"


class CheckpointError(RuntimeError):
    """A checkpoint could not be trusted or loaded: integrity
    verification failed, an npz is unreadable/truncated, or a manifest
    member is missing. Carries the offending path — callers quarantine
    it and fall back to the newest verified checkpoint."""

    def __init__(self, msg: str, path=None):
        super().__init__(msg)
        self.path = Path(path) if path is not None else None


# ------------------------------------------------------------ durability


def _fsync_path(path) -> None:
    """fsync a file or directory by fd — the rename-based atomicity
    story is only durable if the data AND the directory entries are
    forced out before we report success."""
    fd = os.open(path, os.O_RDONLY)
    try:
        os.fsync(fd)
    finally:
        os.close(fd)


# -------------------------------------------------------------- integrity


def _sha256(path) -> str:
    h = hashlib.sha256()
    with open(path, "rb") as f:
        for chunk in iter(lambda: f.read(1 << 20), b""):
            h.update(chunk)
    return h.hexdigest()


def write_manifest(ckpt_dir) -> Path:
    """Per-file SHA-256 manifest over every npz in the directory —
    written INSIDE the atomic tmp dir, so a renamed checkpoint always
    carries its own integrity record."""
    d = Path(ckpt_dir)
    files = {p.name: {"sha256": _sha256(p), "bytes": p.stat().st_size}
             for p in sorted(d.glob("*.npz"))}
    path = d / _MANIFEST
    path.write_text(json.dumps({"version": 1, "files": files},
                               indent=0) + "\n")
    _fsync_path(path)
    return path


def verify(ckpt_dir) -> None:
    """Raise CheckpointError unless the checkpoint's bytes match its
    manifest. Pre-manifest checkpoints (nothing to hash against) pass
    on completeness alone — new saves always write a manifest."""
    d = Path(ckpt_dir)
    man = d / _MANIFEST
    if not man.exists():
        for f in _FILES:
            if not (d / f).exists():
                raise CheckpointError(
                    f"checkpoint {d} is incomplete (no {f}, no "
                    f"manifest)", path=d / f)
        return  # legacy: complete, no manifest to check against
    try:
        listed = json.loads(man.read_text())["files"]
        # valid JSON of the wrong SHAPE (bit rot can keep JSON valid)
        # must quarantine like any other corruption, not escape as a
        # raw TypeError that crashes every supervisor restart
        if not isinstance(listed, dict) or not all(
                isinstance(rec, dict) for rec in listed.values()):
            raise TypeError("manifest 'files' is not a dict of dicts")
    except (OSError, ValueError, KeyError, TypeError) as e:
        raise CheckpointError(
            f"checkpoint {d} has an unreadable manifest ({e})",
            path=man) from e
    for name, rec in sorted(listed.items()):
        p = d / name
        if not p.exists():
            raise CheckpointError(
                f"checkpoint {d}: manifest lists {name} but the file "
                f"is missing", path=p)
        size = p.stat().st_size
        if size != rec.get("bytes"):
            raise CheckpointError(
                f"checkpoint {d}: {name} is {size} bytes, manifest "
                f"says {rec.get('bytes')} (truncated?)", path=p)
        digest = _sha256(p)
        if digest != rec.get("sha256"):
            raise CheckpointError(
                f"checkpoint {d}: {name} SHA-256 mismatch "
                f"({digest[:12]}… != {str(rec.get('sha256'))[:12]}…)",
                path=p)


def is_verified(ckpt_dir) -> bool:
    try:
        verify(ckpt_dir)
        return True
    except CheckpointError:
        return False


def quarantine(ckpt_dir) -> Path | None:
    """Rename a bad checkpoint dir to `ckpt_N.corrupt` (numbered on
    collision) so `latest()` never considers it again but the bytes
    stay available for forensics. Returns the new path, or None when
    the rename lost a race (another process already moved it)."""
    d = Path(ckpt_dir)
    target = d.with_name(d.name + ".corrupt")
    n = 1
    while target.exists():
        n += 1
        target = d.with_name(f"{d.name}.corrupt{n}")
    try:
        d.rename(target)
    except OSError:
        return None
    warnings.warn(f"quarantined corrupt checkpoint {d} -> {target}")
    return target


# ----------------------------------------------------------- pytree <-> npz


def _encode(tree, leaves: list):
    """Deterministic traversal of dict/list/tuple/None nests; appends array
    leaves to `leaves` and returns a JSON-able structure spec."""
    if isinstance(tree, dict):
        keys = sorted(tree)
        return {"kind": "dict", "keys": keys,
                "children": [_encode(tree[k], leaves) for k in keys]}
    if isinstance(tree, (list, tuple)):
        kind = "list" if isinstance(tree, list) else "tuple"
        return {"kind": kind, "children": [_encode(c, leaves) for c in tree]}
    if tree is None:
        return {"kind": "none"}
    leaves.append(np.asarray(jax.device_get(tree)))
    return {"kind": "leaf", "index": len(leaves) - 1}


def _decode(spec, leaves):
    kind = spec["kind"]
    if kind == "dict":
        return {k: _decode(c, leaves)
                for k, c in zip(spec["keys"], spec["children"])}
    if kind == "list":
        return [_decode(c, leaves) for c in spec["children"]]
    if kind == "tuple":
        return tuple(_decode(c, leaves) for c in spec["children"])
    if kind == "none":
        return None
    return leaves[spec["index"]]


def save_pytree(path, tree, meta: dict | None = None) -> None:
    """One npz per pytree: numbered array leaves + JSON spec (+ JSON meta)."""
    leaves: list[np.ndarray] = []
    spec = _encode(tree, leaves)
    payload = {f"leaf_{i}": leaf for i, leaf in enumerate(leaves)}
    payload["spec"] = np.frombuffer(
        json.dumps({"tree": spec, "meta": meta or {}}).encode(), np.uint8)
    np.savez_compressed(path, **payload)


def load_pytree(path, with_meta: bool = False):
    with np.load(path, allow_pickle=False) as z:
        head = json.loads(z["spec"].tobytes().decode())
        n = sum(1 for k in z.files if k.startswith("leaf_"))
        leaves = [z[f"leaf_{i}"] for i in range(n)]
    tree = _decode(head["tree"], leaves)
    return (tree, head["meta"]) if with_meta else tree


# ------------------------------------------------------------- save/restore


def _write_ckpt(ckpt_dir, epoch: int, params, opt_state, meta: dict,
                extra: dict, opt_canon=None, canon_meta=None,
                sync: bool = True, keep: int | None = None) -> Path:
    """The one encoding of the on-disk layout + atomic rename, shared by
    the synchronous and async save paths (they must never drift).

    Multi-controller (round 4): the device->host fetch is COLLECTIVE
    (every process replicates non-addressable leaves together —
    `distributed.fetch_global`), then only process 0 touches the
    filesystem, then a barrier releases the others — so a save at one
    process topology restores at any other.

    SHARED-FILESYSTEM CONTRACT: only process 0 writes, but `restore()`
    reads on EVERY process — multi-host gangs need `ckpt_dir` on a
    filesystem all hosts mount (NFS/GCS-fuse). A host that can't see the
    directory fails fast in `restore()` with this requirement named."""
    from shallowspeed_tpu.distributed import (barrier, fetch_global,
                                              process_zero)

    # collective fetch first, identical order on every process
    params = fetch_global(params)
    opt_state = fetch_global(opt_state)
    extra = {k: fetch_global(v) for k, v in sorted(extra.items())}
    if opt_canon is not None:
        opt_canon = fetch_global(opt_canon)
    from shallowspeed_tpu import chaos

    final = Path(ckpt_dir) / f"ckpt_{epoch}"
    if not process_zero():
        if sync:
            barrier(f"ckpt_{epoch}")
        return final
    chaos.on_save("start")  # fault injection: ENOSPC / kill-in-save
    tmp = Path(ckpt_dir) / f"ckpt_{epoch}.tmp"
    if tmp.exists():
        shutil.rmtree(tmp)
    tmp.mkdir(parents=True)

    def _write(name, tree, meta=None):
        save_pytree(tmp / name, tree, meta=meta)
        # durability: force the bytes out BEFORE the rename publishes
        # the dir — the atomic-rename story is otherwise only atomic
        # against process crashes, not host crashes
        _fsync_path(tmp / name)
        chaos.on_save(f"file:{name}")

    _write("params.npz", params)
    _write("opt.npz", opt_state, meta=meta)
    if opt_canon is not None:
        _write("opt_canon.npz", opt_canon, meta=canon_meta)
    for name, tree in extra.items():
        _write(f"{name}.npz", tree)
    write_manifest(tmp)
    _fsync_path(tmp)
    chaos.on_save("pre_rename")
    if final.exists():
        shutil.rmtree(final)
    tmp.rename(final)
    _fsync_path(final.parent)  # the rename itself must be durable too
    chaos.on_save("renamed")
    if keep:
        prune(ckpt_dir, keep, trusted=final)
    chaos.after_save(final)    # post-hoc corruption faults (after
    #                            rotation: bit rot strikes a COMPLETED
    #                            save, and prune's trusted fast path
    #                            must not vouch for corrupted bytes)
    if sync:
        # releases the other processes only once the rename landed
        barrier(f"ckpt_{epoch}")
    return final


def _canon_opt_export(engine, host_opt_state=None):
    """Engine-agnostic optimizer state for `opt_canon.npz`, or
    (None, None) when none is needed or possible.

    Identity-layout engines (params ARE canonical: the GSPMD family,
    ContextParallelEngine, FusedDPEngine) return None too — their
    `opt.npz` already IS the canonical record (flagged
    `opt_is_canonical` in the main meta), so writing it twice would
    double checkpoint bytes and the device->host fetch for nothing.
    Layout-transforming engines (`PipelineLMEngine`) re-layout
    exactly-params-shaped moments with the same transform their params
    take (`Optimizer.map_state_trees`). `host_opt_state`: an
    already-fetched host copy to reuse (the async saver has one)."""
    opt = getattr(engine, "optimizer", None)
    if opt is None or getattr(engine, "canonical_opt_identity", False):
        return None, None
    meta = {"optimizer": type(opt).__name__}
    custom = getattr(engine, "canon_opt_export", None)
    if custom is not None:  # engines whose state is not one pytree
        canon = custom()    # (the per-stage instruction VM)
        return (None, None) if canon is None else (canon, meta)
    export = getattr(engine, "canon_export_tree", None)
    if export is None:
        return None, None
    if host_opt_state is None:
        from shallowspeed_tpu.distributed import fetch_global

        host_opt_state = fetch_global(engine.opt_state)
    try:
        return opt.map_state_trees(host_opt_state, export), meta
    except ValueError:
        return None, None


def _opt_meta(engine, epoch: int) -> dict:
    opt = getattr(engine, "optimizer", None)
    return {
        "epoch": int(epoch),
        "engine": type(engine).__name__,
        "optimizer": None if opt is None else type(opt).__name__,
        # True => opt.npz doubles as the canonical record (identity
        # layout); cross-engine restore may import it directly
        "opt_is_canonical": bool(
            getattr(engine, "canonical_opt_identity", False)),
    }


def _canon_opt_import(engine, canon):
    """Inverse of `_canon_opt_export`: canonical state -> this engine's
    shape (host-side). None when this engine can't import."""
    if getattr(engine, "canonical_opt_identity", False):
        return canon
    custom = getattr(engine, "canon_opt_import", None)
    if custom is not None:
        return custom(canon)
    imp = getattr(engine, "canon_import_tree", None)
    if imp is None:
        return None
    try:
        return engine.optimizer.map_state_trees(canon, imp)
    except ValueError:
        return None


def _candidates(ckpt_dir) -> list[tuple[int, Path]]:
    """(epoch, path) for every directory that *claims* to be a complete
    checkpoint: a manifest marks completion for new saves; the legacy
    rule (both npz present) covers pre-manifest dirs. `.tmp` leftovers,
    `.corrupt` quarantines, and foreign names never qualify."""
    d = Path(ckpt_dir)
    found = []
    for p in d.iterdir() if d.exists() else ():
        m = re.fullmatch(r"ckpt_(\d+)", p.name)
        if not m:
            continue
        if (p / _MANIFEST).exists() \
                or all((p / f).exists() for f in _FILES):
            found.append((int(m.group(1)), p))
    return sorted(found)


def prune(ckpt_dir, keep: int, trusted=None) -> None:
    """Delete all COMPLETE `ckpt_N` directories except the `keep`
    highest-epoch ones (rotation — a long elastic run otherwise
    accumulates multi-GB checkpoints without bound), but NEVER the
    newest *verified* checkpoint: if everything newer is corrupt, the
    one restorable state must survive rotation, whatever its age.
    `trusted`: a path this process just wrote and hashed (the save
    path passes its own fresh checkpoint) — taken as verified without
    re-reading every npz it fsync'd milliseconds ago. `.tmp` leftovers
    and foreign names are untouched. Process-0-only by construction
    (called from the write path)."""
    assert keep >= 1, f"prune keeps at least one checkpoint, got {keep}"
    found = _candidates(ckpt_dir)
    doomed = found[:-keep or None]
    if doomed:
        trusted = Path(trusted) if trusted is not None else None
        for _, p in reversed(found):
            if p == trusted or is_verified(p):
                doomed = [(e, q) for e, q in doomed if q != p]
                break
    for _, p in doomed:
        shutil.rmtree(p, ignore_errors=True)


def save(ckpt_dir, engine, epoch: int, extra: dict | None = None,
         keep: int | None = None) -> Path:
    """Atomically write `ckpt_dir/ckpt_{epoch}/`: canonical params + engine
    opt state. Writes into `ckpt_{epoch}.tmp/` and renames into place so a
    crash mid-save cannot produce a directory `latest()` would select.

    `extra`: optional {filename-stem: pytree} written INSIDE the atomic
    rename (e.g. the driver's EMA weights) — a crash can never produce a
    checkpoint that `latest()` selects but whose side trees are missing."""
    from shallowspeed_tpu.distributed import fetch_global

    # fetch the opt state ONCE (in a multi-controller run this is a
    # collective all-gather sweep) and share the host copy between the
    # canonical export and the on-disk engine-shaped record
    host_opt = fetch_global(engine.opt_state)
    opt_canon, canon_meta = _canon_opt_export(engine, host_opt)
    return _write_ckpt(
        ckpt_dir, epoch, engine.get_canonical_params(), host_opt,
        _opt_meta(engine, epoch), extra or {}, opt_canon, canon_meta,
        keep=keep)


class AsyncSaver:
    """Non-blocking checkpointing: the device->host snapshot happens on
    the caller's thread (cheap, and it pins the state at the save point),
    then compression + npz writing + the atomic rename run on ONE
    background worker — the training loop never blocks on disk. Saves
    are serialized (a single worker), so checkpoints land in order;
    `wait()` drains the queue (call it before reading `latest()` or
    exiting). Errors surface on the next save()/wait() call rather than
    being swallowed."""

    def __init__(self):
        import queue
        import threading

        # maxsize bounds host memory: each queued save pins a full host
        # snapshot of params+opt state (+EMA); if disk IO is slower than
        # the --save-every cadence, save() backpressures the training
        # loop instead of accumulating snapshots without bound.
        self._q = queue.Queue(maxsize=2)
        self._err = None
        self._thread = threading.Thread(target=self._run, daemon=True)
        self._thread.start()

    def _run(self):
        while True:
            item = self._q.get()
            if item is None:
                self._q.task_done()
                return
            fn = item
            try:
                fn()
            except BaseException as e:  # surfaced on the caller's side
                self._err = e
            finally:
                self._q.task_done()

    def _raise_pending(self):
        if self._err is not None:
            err, self._err = self._err, None
            raise RuntimeError("async checkpoint save failed") from err

    def _raise_collectively(self):
        """Exchange a success bit (a collective, so also a barrier) and
        raise on EVERY process when any peer's background write failed.
        Only process 0 writes, so without the exchange its peers would
        sail past their (empty) pending-error check straight into the
        next collective against a process that is about to die — wedging
        the gang until hang-timeout."""
        from shallowspeed_tpu.distributed import all_ok

        if not all_ok(self._err is None):
            self._raise_pending()  # the failing process re-raises its own
            raise RuntimeError(
                "async checkpoint save failed on a peer process")

    def save(self, ckpt_dir, engine, epoch: int,
             extra: dict | None = None, keep: int | None = None) -> None:
        """Snapshot now, write later. The snapshot is a host copy, so
        the engine may keep training (and donating buffers) immediately.
        The snapshot fetch runs on the CALLER's thread — in a
        multi-controller run it is collective (fetch_global), and doing
        it here (not on the writer thread) keeps every process's
        collective order identical to its training stream."""
        from shallowspeed_tpu.distributed import fetch_global

        self._raise_collectively()
        params = fetch_global(engine.get_canonical_params())
        opt_state = fetch_global(engine.opt_state)
        opt_canon, canon_meta = _canon_opt_export(engine, opt_state)
        extra_host = {k: fetch_global(v)
                      for k, v in sorted((extra or {}).items())}
        meta = _opt_meta(engine, epoch)

        def write():
            # sync=False: no collectives on the writer thread (they
            # would interleave with the training stream's); wait()
            # barriers on the caller's thread instead
            _write_ckpt(ckpt_dir, epoch, params, opt_state, meta,
                        extra_host, opt_canon, canon_meta, sync=False,
                        keep=keep)

        self._q.put(write)

    def wait(self) -> None:
        """Block until every queued save is on disk; re-raise failures.
        Multi-controller: exchanges a success bit collectively (which is
        also the drain barrier), so if process 0's background write
        failed EVERY process raises here together — peers never proceed
        trusting `latest()` while process 0 is about to die (which would
        wedge the gang until hang-timeout)."""
        self._q.join()
        self._raise_collectively()

    def close(self) -> None:
        """Drain, stop the worker, re-raise failures LOCALLY. No
        collective here: close() runs on exception/teardown paths where
        peers may be anywhere (a one-process failure must exit promptly,
        not block in a collective until hang-timeout). Multi-controller
        clean-shutdown callers should `wait()` first — that is the
        collective everyone-raises-together point."""
        self._q.join()
        self._q.put(None)
        self._q.join()
        self._raise_pending()


def has_checkpoint(ckpt_dir) -> bool:
    """Whether any complete-looking checkpoint exists — a cheap
    existence probe (no hashing). The auto-resume gate uses this and
    leaves verification/quarantine/fallback to `restore_latest`, so
    the newest multi-GB checkpoint is hashed once at restore, not
    twice (the re-hash would inflate measured restart downtime)."""
    return bool(_candidates(ckpt_dir))


def latest(ckpt_dir) -> Path | None:
    """Highest-epoch VERIFIED checkpoint directory (ignores `.tmp`
    leftovers, foreign `ckpt_*` names, and incomplete dirs). A complete
    dir that fails manifest verification is quarantined as
    `ckpt_N.corrupt` on the spot and the scan falls back to the next
    newest — `latest()` never hands out a checkpoint whose bytes don't
    match their recorded hashes. (Multi-process: the quarantine rename
    races benignly — one process wins, the others' rename fails and
    their next scan no longer sees the dir.)"""
    for _, p in reversed(_candidates(ckpt_dir)):
        if is_verified(p):
            return p
        quarantine(p)
    return None


def _structure_mismatch(a, b) -> str | None:
    """None if same pytree structure + leaf shapes, else a description."""
    la, ta = tree_flatten(a)
    lb, tb = tree_flatten(b)
    if ta != tb:
        return f"pytree structure {ta} != {tb}"
    for i, (x, y) in enumerate(zip(la, lb)):
        if np.shape(x) != np.shape(y):
            return f"leaf {i} shape {np.shape(x)} != {np.shape(y)}"
    return None


def _restore_opt_canonical(engine, d: Path, opt_state, meta) -> bool:
    """Try the engine-agnostic optimizer record: `opt_canon.npz` if
    present, else `opt.npz` itself when its meta says the writing
    engine's layout was canonical (identity engines skip the duplicate
    file). Returns True when the state was installed."""
    path = d / "opt_canon.npz"
    if path.exists():
        canon, cmeta = _load_checked(path, with_meta=True)
        src_kind = cmeta.get("optimizer")
    elif meta.get("opt_is_canonical"):
        canon, src_kind = opt_state, meta.get("optimizer")
    else:
        return False
    opt = getattr(engine, "optimizer", None)
    if opt is None or src_kind != type(opt).__name__:
        if opt is not None:
            warnings.warn(
                f"canonical opt state is {src_kind} but this "
                f"engine runs {type(opt).__name__}; re-initializing")
        return False
    state = _canon_opt_import(engine, canon)
    if state is None:
        return False
    mismatch = _structure_mismatch(state, engine.opt_state)
    if mismatch is not None:
        warnings.warn(f"canonical opt state does not match this engine's "
                      f"optimizer topology ({mismatch}); re-initializing")
        return False
    engine.set_opt_state(state)
    return True


def _load_checked(path, with_meta: bool = False):
    """load_pytree with every load-path failure — truncated zip, bad
    JSON spec, missing members, IO errors — wrapped into the one typed
    CheckpointError carrying the offending path. Callers never see a
    raw zipfile.BadZipFile."""
    import zipfile

    try:
        return load_pytree(path, with_meta=with_meta)
    except (OSError, ValueError, KeyError, EOFError,
            zipfile.BadZipFile, json.JSONDecodeError) as e:
        # np.load raises OSError on short reads, ValueError on pickle
        # refusal, and lets zipfile.BadZipFile escape on a mangled
        # archive — all of them become the one typed error here
        raise CheckpointError(
            f"checkpoint file {path} failed to load "
            f"({type(e).__name__}: {e})", path=path) from e


def restore(engine, ckpt_path) -> int:
    """Load a checkpoint into `engine` (any kind). Returns the next epoch.

    The manifest is verified BEFORE anything is installed (a corrupt
    checkpoint raises CheckpointError — quarantine-and-fall-back is
    `restore_latest`'s job), and every load failure is wrapped into
    CheckpointError with the offending path. Params are additionally
    validated (structure + shapes) against the engine's model config; a
    mismatch raises ValueError instead of silently installing wrong
    weights. Optimizer state restores only when its pytree matches the
    engine's (same kind AND same topology — opt state is engine-shaped,
    e.g. stacked per-stage for the SPMD engine).
    """
    d = Path(ckpt_path)
    if not (d / "params.npz").exists():
        msg = f"checkpoint {d} has no params.npz"
        if jax.process_count() > 1:
            # only diagnose the shared-FS contract when it can apply —
            # a single-process wrong --resume path gets the plain error
            msg += (f" (process {jax.process_index()} of "
                    f"{jax.process_count()}). Multi-controller restore "
                    f"reads on every process while save writes only on "
                    f"process 0 — the checkpoint dir must live on a "
                    f"filesystem ALL hosts mount (see _write_ckpt's "
                    f"shared-filesystem contract)")
        raise CheckpointError(msg, path=d / "params.npz")
    verify(d)
    params = _load_checked(d / "params.npz")
    mismatch = _structure_mismatch(params, engine.get_canonical_params())
    if mismatch is not None:
        raise ValueError(
            f"checkpoint {d} does not match this engine's model config "
            f"({mismatch}); refusing to restore")
    engine.set_canonical_params(params)
    opt_state, meta = _load_checked(d / "opt.npz", with_meta=True)
    if (meta["engine"] == type(engine).__name__
            and _structure_mismatch(opt_state, engine.opt_state) is None):
        engine.set_opt_state(opt_state)
    elif len(jax.tree_util.tree_leaves(opt_state)) > 0:
        # cross-engine: the canonical (per-layer, unpadded) moment record
        # makes e.g. a dp=4 Adam checkpoint resume EXACTLY into dp=2 x
        # pp=4 — the same engine-agnosticism params have always had
        restored = _restore_opt_canonical(engine, d, opt_state, meta)
        if not restored:
            warnings.warn(
                f"checkpoint opt state is {meta['engine']}-shaped and "
                f"does not match this {type(engine).__name__}'s topology "
                f"(no importable canonical record); re-initializing")
    nxt = int(meta["epoch"]) + 1
    if hasattr(engine, "_step_count"):
        # dropout keys derive from the per-engine step counter: resume it
        # at the global step so a resumed run draws the SAME mask stream
        # an uninterrupted run would (train_lm's exact-resume contract)
        engine._step_count = nxt
    return nxt


def restore_latest(engine, ckpt_dir
                   ) -> tuple[int, Path | None, list[Path]]:
    """Restore the newest checkpoint that both verifies AND loads,
    quarantining every one that doesn't and falling back — the recovery
    path `--auto-resume` rides after a corruption fault. Returns
    `(next_epoch, restored_path, quarantined_paths)`; `(0, None, [...])`
    when nothing restorable remains. Config mismatches (ValueError)
    still propagate: a wrong --resume target is a user error, not
    corruption to quarantine."""
    quarantined: list[Path] = []
    while True:
        cands = _candidates(ckpt_dir)
        if not cands:
            return 0, None, quarantined
        _, ck = cands[-1]
        try:
            return restore(engine, ck), ck, quarantined
        except CheckpointError as e:
            # covers manifest-verification failures AND unloadable
            # trees in legacy (no-manifest) dirs: same treatment
            warnings.warn(f"restore of {ck} failed ({e}); quarantining "
                          f"and falling back")
            q = quarantine(ck)
            if q is not None:
                quarantined.append(q)
            elif ck.exists():
                # the dir is still there but could not be renamed (a
                # read-only FS): bail rather than spin on the same dir
                return 0, None, quarantined
            # else: a peer process won the quarantine race and the dir
            # is gone — rescan and keep falling back like the peer did
            # (returning (0, None) here would silently start THIS gang
            # member fresh while its peers resumed from a checkpoint)
