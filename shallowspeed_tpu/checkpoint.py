"""Checkpoint / resume — save and restore training state.

The reference has no checkpointing (SURVEY §5: weights live only in memory,
`/root/reference/shallowspeed/layers.py:17-28`; the only serialization in its
repo is the PyTorch baseline's `torch.save`,
`scripts/DDP_PyTorch_MNIST.py:157-161`). This subsystem goes beyond parity:

- **Canonical format**: model parameters are stored engine-agnostically as
  the flat list of layer dicts `[{"W", "b"}, ...]` over the *whole* model
  (the pp=1 view). Every engine can export/import it, so a checkpoint
  written by a dp=4 fused run restores into a dp=2 x pp=4 SPMD run — the
  payoff of the reference's deterministic partitioning design
  (`layers.py:104-113`) carried over to serialized state.
- **Optimizer state** is engine-shaped (stacked/padded for the SPMD engine),
  so it round-trips exactly when the engine kind matches and is re-initialized
  otherwise (with a warning) — resuming SGD is always exact since its state
  is empty.
- On-disk format: a single `.npz` (flattened leaves + a pickled treedef),
  self-contained — no orbax dependency, loadable with plain numpy.
"""

from __future__ import annotations

import pickle
import warnings
from pathlib import Path

import jax
import numpy as np

tree_flatten = jax.tree_util.tree_flatten
tree_unflatten = jax.tree_util.tree_unflatten


def _to_host(tree):
    return jax.tree_util.tree_map(
        lambda l: np.asarray(jax.device_get(l)), tree)


def save_pytree(path, tree) -> None:
    """One npz per pytree: leaves as arrays, structure pickled alongside."""
    leaves, treedef = tree_flatten(_to_host(tree))
    payload = {f"leaf_{i}": leaf for i, leaf in enumerate(leaves)}
    payload["treedef"] = np.frombuffer(pickle.dumps(treedef), np.uint8)
    np.savez_compressed(path, **payload)


def load_pytree(path):
    with np.load(path, allow_pickle=False) as z:
        treedef = pickle.loads(z["treedef"].tobytes())
        n = sum(1 for k in z.files if k.startswith("leaf_"))
        leaves = [z[f"leaf_{i}"] for i in range(n)]
    return tree_unflatten(treedef, leaves)


def save(ckpt_dir, engine, epoch: int) -> Path:
    """Write `ckpt_dir/ckpt_{epoch}/`: canonical params + engine opt state."""
    d = Path(ckpt_dir) / f"ckpt_{epoch}"
    d.mkdir(parents=True, exist_ok=True)
    save_pytree(d / "params.npz", engine.get_canonical_params())
    state = {"epoch": epoch, "engine": type(engine).__name__,
             "opt_state": _to_host(engine.opt_state)}
    save_pytree(d / "opt.npz", state)
    return d


def latest(ckpt_dir) -> Path | None:
    d = Path(ckpt_dir)
    if not d.exists():
        return None
    ckpts = sorted(d.glob("ckpt_*"), key=lambda p: int(p.name.split("_")[1]))
    return ckpts[-1] if ckpts else None


def _same_structure(a, b) -> bool:
    la, ta = tree_flatten(a)
    lb, tb = tree_flatten(b)
    return ta == tb and all(
        np.shape(x) == np.shape(y) for x, y in zip(la, lb))


def restore(engine, ckpt_path) -> int:
    """Load a checkpoint into `engine` (any kind). Returns the next epoch.

    Params restore via the canonical format; optimizer state restores only
    when its pytree matches the engine's (same kind AND same topology —
    opt state is engine-shaped, e.g. stacked per-stage for the SPMD engine).
    """
    d = Path(ckpt_path)
    engine.set_canonical_params(load_pytree(d / "params.npz"))
    state = load_pytree(d / "opt.npz")
    if (state["engine"] == type(engine).__name__
            and _same_structure(state["opt_state"], engine.opt_state)):
        engine.set_opt_state(state["opt_state"])
    elif len(jax.tree_util.tree_leaves(state["opt_state"])) > 0:
        warnings.warn(
            f"checkpoint opt state is {state['engine']}-shaped and does not "
            f"match this {type(engine).__name__}'s topology; re-initializing")
    return int(state["epoch"]) + 1
