"""Mixture-of-experts ops: capacity-based top-k routing + einsum dispatch.

The reference has no MoE / expert parallelism (SURVEY §2 parallelism
checklist: EP absent); this module adds the family the TPU-native way — the
GShard/Switch formulation rather than gather/scatter token shuffling:

- **Static shapes everywhere.** Each expert processes a fixed-capacity
  buffer of `C` token slots per batch group; routing produces dense
  `dispatch`/`combine` tensors `(G, S, E, C)` and the actual token movement
  is two einsums. Nothing here has data-dependent shapes, so the whole layer
  jits, vmaps, and shards like any matmul stack.
- **Expert parallelism is a placement decision.** Stacked expert weights
  `(E, d, ff)` shard over an `ep` mesh axis via `PartitionSpec('ep', ...)`;
  the dispatch einsum's output `(E, G, C, d)` is likewise `ep`-sharded, and
  GSPMD lowers the resharding between the token-sharded and expert-sharded
  layouts to the all-to-all collective that NCCL-style frameworks hand-code
  (see `parallel/expert.py`).
- **Load balancing** uses the standard Switch-Transformer auxiliary loss
  (fraction-routed x mean-probability per expert, scaled by E), plus the
  optional router z-loss (ST-MoE, Zoph et al.): mean(logsumexp(logits)^2)
  penalizes router-logit drift, the standard stabilizer for long MoE runs
  (large logits make top-k selections brittle, especially under bf16).
"""

from __future__ import annotations

import math

import jax
import jax.numpy as jnp


def expert_capacity(seq_len: int, num_experts: int, top_k: int,
                    capacity_factor: float) -> int:
    """Token slots per expert per batch group (static)."""
    return max(1, math.ceil(top_k * seq_len * capacity_factor / num_experts))


def topk_capacity_routing(gate_logits: jax.Array, capacity: int,
                          top_k: int = 2, priority: bool = False):
    """GShard-style top-k routing with per-expert capacity.

    gate_logits: (G, S, E) — G batch groups of S tokens over E experts.

    `priority=True` switches slot assignment from sequence order to
    BATCH-PRIORITY routing (Riquelme et al., V-MoE): within each k,
    tokens claim an expert's slots in descending gate-weight order, so
    when an expert overflows it drops its LOWEST-confidence assignments
    instead of whatever came late in the sequence. The drop *count* at
    fixed capacity is unchanged (overflow is overflow) — what improves
    is which mass survives: the kept fraction of total gate weight
    rises, and with it loss at aggressive capacity factors. Positional
    bias goes away too (sequence order stops mattering).

    Returns:
      combine:  (G, S, E, C) float32 — combine[g, s, e, c] is token (g, s)'s
                gate weight on expert e's slot c (0 if not routed there).
      dispatch: (G, S, E, C) bool — nonzero support of `combine`.
      aux:      scalar load-balancing loss (Switch formulation).
      stats:    {"load": (E,) f32 — fraction of (token, k) assignments
                routed to each expert (pre-drop; sums to 1),
                "drop_fraction": scalar f32 — fraction of assignments
                dropped for capacity}. Routing is stop-gradiented by
                construction here (top_k indices), so consumers may log
                these without touching the loss; unused stats are
                dead-code-eliminated by XLA.

    Tokens beyond an expert's capacity are dropped for that expert (their
    gate weight contributes nothing) — the standard static-shape tradeoff.
    The drop is SILENT in the loss (the renormalized gate mass simply
    never reaches an expert), which is exactly why `stats` exists: a
    capacity_factor too low for the current routing entropy shows up as
    drop_fraction, not as an error.
    Positions are assigned in sequence order per expert, with later k
    choices stacked after all earlier-k assignments (GShard's ordering).
    """
    g, s, e = gate_logits.shape
    probs = jax.nn.softmax(gate_logits.astype(jnp.float32), axis=-1)

    # Top-k expert choices per token, gates renormalized over the chosen k.
    raw_gate, topk_idx = jax.lax.top_k(probs, top_k)           # (G, S, K)
    topk_gate = raw_gate / (raw_gate.sum(-1, keepdims=True) + 1e-9)

    combine = jnp.zeros((g, s, e, capacity), jnp.float32)
    used = jnp.zeros((g, e), jnp.float32)  # slots consumed by earlier k
    kept = jnp.float32(0.0)
    assigned = jnp.zeros((e,), jnp.float32)  # pre-drop per-expert counts
    for k in range(top_k):
        onehot = jax.nn.one_hot(topk_idx[..., k], e)            # (G, S, E)
        if priority:
            # Batch-priority: rank this k's assignments per expert by
            # the RAW router probability (descending; stable, so
            # sequence order breaks ties) — the renormalized gate would
            # degenerate to 1.0 at top_k=1. Unassigned tokens score 0
            # and sort after every positive-gate assignment, so ranks
            # below `capacity` are exactly the top-gated claimants.
            score = onehot * raw_gate[..., k, None]             # (G, S, E)
            order = jnp.argsort(-score, axis=1)                 # (G, S, E)
            rank = jnp.argsort(order, axis=1).astype(jnp.float32)
            pos = rank + used[:, None, :]
        else:
            # Sequence order: tokens assigned earlier in the sequence
            # (or by an earlier k) occupy lower slots (GShard).
            pos = jnp.cumsum(onehot, axis=1) - onehot + used[:, None, :]
        keep = onehot * (pos < capacity)                        # (G, S, E)
        slot = jax.nn.one_hot((pos * onehot).sum(-1).astype(jnp.int32),
                              capacity)                         # (G, S, C)
        combine = combine + (topk_gate[..., k, None, None]
                             * keep[..., None] * slot[:, :, None, :])
        used = used + keep.sum(axis=1)
        kept = kept + keep.sum()
        assigned = assigned + onehot.sum(axis=(0, 1))
    dispatch = combine > 0.0

    # Switch aux loss on the top-1 assignment: E * sum_e f_e * P_e, where
    # f_e = fraction of tokens whose first choice is e, P_e = mean prob.
    top1 = jax.nn.one_hot(topk_idx[..., 0], e)
    aux = e * jnp.sum(top1.mean(axis=(0, 1)) * probs.mean(axis=(0, 1)))
    total = jnp.float32(g * s * top_k)
    stats = {"load": assigned / total,
             "drop_fraction": 1.0 - kept / total}
    return combine, dispatch, aux, stats


def router_z_loss(gate_logits: jax.Array) -> jax.Array:
    """ST-MoE router z-loss: mean over tokens of logsumexp(logits)^2 —
    pulls the router's log-partition toward 0 without touching the
    routing distribution's shape."""
    z = jax.nn.logsumexp(gate_logits.astype(jnp.float32), axis=-1)
    return jnp.mean(z * z)


def moe_ffn(p: dict, x: jax.Array, top_k: int, capacity_factor: float,
            priority: bool = False, axis_name: str | None = None):
    """Mixture-of-experts feed-forward layer (drop-in for the dense GELU MLP).

    p: {"gate": (d, E), "wi": (E, d, ff), "bi": (E, ff),
        "wo": (E, ff, d), "bo": (E, d)}
    x: (G, S, d) -> (y (G, S, d), balance-aux scalar, router z-loss
    scalar, routing stats dict) — the auxiliaries come back UNWEIGHTED;
    the model config owns the weights (`moe_aux_weight`, `moe_z_weight`).
    `stats` (see `topk_capacity_routing`) is observability only — when a
    caller drops it, XLA dead-code-eliminates its computation.

    The two routing einsums below are where expert parallelism happens: with
    `wi`/`wo` sharded `P('ep', ...)` and `x` sharded over batch, GSPMD turns
    the (G,S,·)->(E,G,C,·) layout change into an all-to-all over 'ep'.

    `axis_name` (shard_map contexts only — see `moe_ffn_ep`): route the
    dispatch/combine buffers through an EXPLICIT `lax.all_to_all` pair
    over that mesh axis; `p` then holds this device's E/ep expert shard
    while the gate stays global. One body serves both paths, so the
    routing math cannot drift between them."""
    g, s, d = x.shape
    e = p["gate"].shape[1]                     # GLOBAL expert count
    cap = expert_capacity(s, e, top_k, capacity_factor)

    # Router in f32 regardless of compute dtype: bf16 gate logits can flip
    # top-k selections (routing is stability-critical; the softmax in
    # topk_capacity_routing is f32 already).
    logits = jnp.einsum("gsd,de->gse", x, p["gate"],
                        preferred_element_type=jnp.float32)     # (G, S, E)
    combine, dispatch, aux, stats = topk_capacity_routing(
        logits, cap, top_k, priority=priority)

    xin = jnp.einsum("gsec,gsd->egcd", dispatch.astype(x.dtype), x)
    if axis_name is not None:
        # (E, G, C, d) -> (E_local, ep*G, C, d): peer j receives every
        # peer's rows [j*E_local, (j+1)*E_local) — matching the
        # contiguous P(..., 'ep', ...) shard of the stacked expert
        # weights — blocks ordered by source peer on the group axis
        xin = jax.lax.all_to_all(xin, axis_name, split_axis=0,
                                 concat_axis=1, tiled=True)
    h = jax.nn.gelu(jnp.einsum("egcd,edf->egcf", xin, p["wi"])
                    + p["bi"][:, None, None, :])
    out = (jnp.einsum("egcf,efd->egcd", h, p["wo"])
           + p["bo"][:, None, None, :])
    if axis_name is not None:
        # inverse: scatter the group axis back, gather the expert axis
        out = jax.lax.all_to_all(out, axis_name, split_axis=1,
                                 concat_axis=0, tiled=True)
    y = jnp.einsum("gsec,egcd->gsd", combine.astype(x.dtype), out)
    return y, aux, router_z_loss(logits), stats


def moe_ffn_ep(p: dict, x: jax.Array, top_k: int, capacity_factor: float,
               axis_name: str = "ep", priority: bool = False):
    """`moe_ffn` for shard_map contexts — the expert parallelism is an
    EXPLICIT `lax.all_to_all`, not a GSPMD placement decision (inside
    shard_map there is no GSPMD to lower the resharding; same reason
    `ulysses_attention` hand-writes its head<->sequence all-to-alls).

    p: this device's expert shard — gate (d, E) REPLICATED over the ep
    axis (every token routes over all E global experts), wi/bi/wo/bo
    carrying only E/ep experts (leading dim E_local).
    x: (G, S, d) — this device's LOCAL tokens (the ep axis shards rows,
    multiplying dp for the data dimension).

    Dispatch: route locally over global E, build the (E, G, C, d)
    buffer, then all-to-all — scatter the expert axis, gather the group
    axis — so each device holds (E_local, ep*G, C, d): its own experts'
    slots from EVERY ep peer (the DeepSpeed-MoE / Tutel a2a pair,
    ridden over ICI here). Expert FFN runs local; the inverse a2a
    returns (E, G, C, d) and the combine einsum is local again.

    The body IS `moe_ffn` (one shared implementation — the routing math
    cannot drift between the GSPMD and explicit-collective paths):
    capacity competition is per (group row, expert) and each row is its
    own group, so resharding rows across dp x ep changes NOTHING about
    who gets dropped — asserted by the dp-only parity tests.

    Aux/z losses are means over LOCAL tokens; the caller owns the
    pmean over the data axes (('dp', 'ep') in the pipeline engine)."""
    e = p["gate"].shape[1]
    e_loc = p["wi"].shape[0]
    assert e % e_loc == 0, (e, e_loc)
    return moe_ffn(p, x, top_k, capacity_factor, priority=priority,
                   axis_name=axis_name)
