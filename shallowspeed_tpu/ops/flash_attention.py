"""Flash attention — fused blockwise attention as Pallas TPU kernels.

The hot op of the transformer family (`models/transformer.py`). XLA compiles
the naive `ops.attention` into einsum+softmax+einsum with the full (T, T)
score matrix materialized in HBM; this kernel computes attention blockwise in
VMEM with an online softmax (the FlashAttention-2 formulation), so HBM
traffic is O(T·D) instead of O(T²) and the MXU stays fed from on-chip
memory. Three kernels:

- forward: per (batch·head, q-block) grid cell, fori_loop over k-blocks with
  running (max m, normalizer l, accumulator acc) state; causal masking skips
  whole k-blocks past the diagonal (the loop bound itself shrinks). Saves
  the log-sum-exp for the backward.
- backward-dq: same q-block grid; recomputes p from (q, k, lse), forms
  ds = p * (dp - delta) and accumulates dq = Σ ds·k.
- backward-dkv: k-block grid; loops over the q-blocks at/after the diagonal
  accumulating dv = Σ pᵀ·do and dk = Σ dsᵀ·q.

Wrapped in `jax.custom_vjp`, so `jax.grad` through the transformer uses the
fused backward. On non-TPU backends the kernels run in Pallas interpret mode
(exact same code path, used by the CPU test suite); on TPU they compile via
Mosaic. Layout contract matches `ops.attention`: (batch, seq, heads,
head_dim).

Written per /opt/skills/guides/pallas_guide.md (blockwise VMEM tiling,
online-softmax accumulators, preferred_element_type=f32 on every MXU dot,
@pl.when for edge blocks).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl

_NEG = -1e30  # plain float: jnp scalars would be captured consts in kernels
_LANES = 128  # Mosaic min lane width: row stats (lse/delta) pad to this


def _interpret_default() -> bool:
    return jax.default_backend() != "tpu"


# ----------------------------------------------------------------- forward


def _fwd_kernel(q_ref, k_ref, v_ref, o_ref, lse_ref, *, scale, causal,
                block_q, block_k, seq_k):
    iq = pl.program_id(1)
    q = q_ref[:].astype(jnp.float32)                       # (bq, D)
    d = q.shape[-1]

    nkb = seq_k // block_k
    if causal:
        # q rows of this block end at global row iq*bq + bq - 1; k blocks
        # strictly past that are fully masked — shrink the loop bound.
        last = (iq * block_q + block_q - 1) // block_k
        nkb_eff = jnp.minimum(nkb, last + 1)
    else:
        nkb_eff = nkb

    qrow = iq * block_q + jax.lax.broadcasted_iota(
        jnp.int32, (block_q, block_k), 0)

    def body(j, carry):
        m, l, acc = carry
        kb = k_ref[pl.ds(j * block_k, block_k), :].astype(jnp.float32)
        vb = v_ref[pl.ds(j * block_k, block_k), :].astype(jnp.float32)
        s = jnp.dot(q, kb.T, preferred_element_type=jnp.float32) * scale
        if causal:
            kcol = j * block_k + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 1)
            valid = qrow >= kcol
            s = jnp.where(valid, s, _NEG)
        m_new = jnp.maximum(m, s.max(axis=-1, keepdims=True))
        p = jnp.exp(s - m_new)
        if causal:
            p = jnp.where(valid, p, 0.0)
        alpha = jnp.exp(m - m_new)
        l_new = l * alpha + p.sum(axis=-1, keepdims=True)
        acc_new = acc * alpha + jnp.dot(
            p, vb, preferred_element_type=jnp.float32)
        return m_new, l_new, acc_new

    m0 = jnp.full((block_q, 1), _NEG)
    l0 = jnp.zeros((block_q, 1), jnp.float32)
    acc0 = jnp.zeros((block_q, d), jnp.float32)
    m, l, acc = jax.lax.fori_loop(0, nkb_eff, body, (m0, l0, acc0))

    o_ref[:] = (acc / jnp.maximum(l, 1e-30)).astype(o_ref.dtype)
    # row stats broadcast across a 128-lane dim (Mosaic min tile width)
    lse_ref[:] = jnp.broadcast_to(
        m + jnp.log(jnp.maximum(l, 1e-30)), (block_q, _LANES))


# ---------------------------------------------------------------- backward


def _dq_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref, dq_ref, *,
               scale, causal, block_q, block_k, seq_k):
    iq = pl.program_id(1)
    q = q_ref[:].astype(jnp.float32)
    do = do_ref[:].astype(jnp.float32)
    lse = lse_ref[:, 0:1]
    delta = delta_ref[:, 0:1]
    d = q.shape[-1]

    nkb = seq_k // block_k
    if causal:
        last = (iq * block_q + block_q - 1) // block_k
        nkb_eff = jnp.minimum(nkb, last + 1)
    else:
        nkb_eff = nkb

    qrow = iq * block_q + jax.lax.broadcasted_iota(
        jnp.int32, (block_q, block_k), 0)

    def body(j, dq):
        kb = k_ref[pl.ds(j * block_k, block_k), :].astype(jnp.float32)
        vb = v_ref[pl.ds(j * block_k, block_k), :].astype(jnp.float32)
        s = jnp.dot(q, kb.T, preferred_element_type=jnp.float32) * scale
        if causal:
            kcol = j * block_k + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 1)
            s = jnp.where(qrow >= kcol, s, _NEG)
        p = jnp.exp(s - lse)
        dp = jnp.dot(do, vb.T, preferred_element_type=jnp.float32)
        ds = p * (dp - delta) * scale
        return dq + jnp.dot(ds, kb, preferred_element_type=jnp.float32)

    dq = jax.lax.fori_loop(
        0, nkb_eff, body, jnp.zeros((block_q, d), jnp.float32))
    dq_ref[:] = dq.astype(dq_ref.dtype)


def _dkv_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
                dk_ref, dv_ref, *, scale, causal, block_q, block_k, seq_q):
    jk = pl.program_id(1)
    kb = k_ref[:].astype(jnp.float32)                      # (bk, D)
    vb = v_ref[:].astype(jnp.float32)
    d = kb.shape[-1]

    nqb = seq_q // block_q
    if causal:
        # q blocks strictly before this k block are fully masked
        first = (jk * block_k) // block_q
    else:
        first = 0

    kcol = jk * block_k + jax.lax.broadcasted_iota(
        jnp.int32, (block_q, block_k), 1)

    def body(i, carry):
        dk, dv = carry
        qb = q_ref[pl.ds(i * block_q, block_q), :].astype(jnp.float32)
        dob = do_ref[pl.ds(i * block_q, block_q), :].astype(jnp.float32)
        lse = lse_ref[pl.ds(i * block_q, block_q), 0:1]
        delta = delta_ref[pl.ds(i * block_q, block_q), 0:1]
        s = jnp.dot(qb, kb.T, preferred_element_type=jnp.float32) * scale
        if causal:
            qrow = i * block_q + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 0)
            s = jnp.where(qrow >= kcol, s, _NEG)
        p = jnp.exp(s - lse)
        dv = dv + jnp.dot(p.T, dob, preferred_element_type=jnp.float32)
        dp = jnp.dot(dob, vb.T, preferred_element_type=jnp.float32)
        ds = p * (dp - delta) * scale
        dk = dk + jnp.dot(ds.T, qb, preferred_element_type=jnp.float32)
        return dk, dv

    dk0 = jnp.zeros((block_k, d), jnp.float32)
    dv0 = jnp.zeros((block_k, d), jnp.float32)
    dk, dv = jax.lax.fori_loop(first, nqb, body, (dk0, dv0))
    dk_ref[:] = dk.astype(dk_ref.dtype)
    dv_ref[:] = dv.astype(dv_ref.dtype)


# ------------------------------------------------------------- entry points


def _to_bhsd(x):
    """(B, T, H, D) -> (B*H, T, D) for the (batch·head, block) grid."""
    b, t, h, d = x.shape
    return jnp.reshape(jnp.transpose(x, (0, 2, 1, 3)), (b * h, t, d))


def _from_bhsd(x, b, h):
    bh, t, d = x.shape
    return jnp.transpose(jnp.reshape(x, (b, h, t, d)), (0, 2, 1, 3))


def _pick_block(t: int, want: int) -> int:
    while t % want:
        want //= 2
    return max(want, 1)


def _sds(shape, dtype, like):
    """ShapeDtypeStruct inheriting `like`'s shard_map variance (vma), so the
    kernels compose with explicit-sharding engines (pallas_call under
    shard_map requires explicit output vma)."""
    vma = getattr(getattr(like, "aval", None), "vma", None)
    if vma:
        return jax.ShapeDtypeStruct(shape, dtype, vma=vma)
    return jax.ShapeDtypeStruct(shape, dtype)


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6))
def flash_attention(q, k, v, causal: bool = True, block_q: int = 128,
                    block_k: int = 128, interpret: bool | None = None):
    """Fused multi-head attention; same contract as `ops.attention`.

    q, k, v: (batch, seq, heads, head_dim) -> (batch, seq, heads, head_dim).
    Sequence lengths must be divisible by the (auto-shrunk) block sizes.
    `interpret=None` auto-selects Pallas interpret mode off-TPU.
    """
    o, _ = _flash_fwd(q, k, v, causal, block_q, block_k, interpret)
    return o


def _flash_fwd(q, k, v, causal, block_q, block_k, interpret):
    if interpret is None:
        interpret = _interpret_default()
    b, tq, h, d = q.shape
    tk = k.shape[1]
    bq = _pick_block(tq, block_q)
    bk = _pick_block(tk, block_k)
    scale = 1.0 / float(np.sqrt(d))

    q3, k3, v3 = _to_bhsd(q), _to_bhsd(k), _to_bhsd(v)
    bh = b * h

    kernel = functools.partial(_fwd_kernel, scale=scale, causal=causal,
                               block_q=bq, block_k=bk, seq_k=tk)
    o3, lse = pl.pallas_call(
        kernel,
        grid=(bh, tq // bq),
        in_specs=[
            pl.BlockSpec((None, bq, d), lambda i, j: (i, j, 0)),
            pl.BlockSpec((None, tk, d), lambda i, j: (i, 0, 0)),
            pl.BlockSpec((None, tk, d), lambda i, j: (i, 0, 0)),
        ],
        out_specs=[
            pl.BlockSpec((None, bq, d), lambda i, j: (i, j, 0)),
            pl.BlockSpec((None, bq, _LANES), lambda i, j: (i, j, 0)),
        ],
        out_shape=[
            _sds((bh, tq, d), q.dtype, q3),
            _sds((bh, tq, _LANES), jnp.float32, q3),
        ],
        interpret=interpret,
    )(q3, k3, v3)
    return _from_bhsd(o3, b, h), (q, k, v, _from_bhsd(o3, b, h), lse)


def _flash_fwd_rule(q, k, v, causal, block_q, block_k, interpret):
    o, res = _flash_fwd(q, k, v, causal, block_q, block_k, interpret)
    return o, res


def _flash_bwd_rule(causal, block_q, block_k, interpret, res, do):
    q, k, v, o, lse = res
    if interpret is None:
        interpret = _interpret_default()
    b, tq, h, d = q.shape
    tk = k.shape[1]
    bq = _pick_block(tq, block_q)
    bk = _pick_block(tk, block_k)
    scale = 1.0 / float(np.sqrt(d))
    bh = b * h

    q3, k3, v3 = _to_bhsd(q), _to_bhsd(k), _to_bhsd(v)
    o3, do3 = _to_bhsd(o), _to_bhsd(do)
    # delta_i = rowsum(dO_i * O_i) — the softmax-jacobian diagonal term,
    # broadcast across the 128-lane stats dim like lse
    delta = jnp.broadcast_to(
        jnp.sum(do3.astype(jnp.float32) * o3.astype(jnp.float32),
                axis=-1, keepdims=True),
        lse.shape)

    dq_kernel = functools.partial(_dq_kernel, scale=scale, causal=causal,
                                  block_q=bq, block_k=bk, seq_k=tk)
    dq3 = pl.pallas_call(
        dq_kernel,
        grid=(bh, tq // bq),
        in_specs=[
            pl.BlockSpec((None, bq, d), lambda i, j: (i, j, 0)),
            pl.BlockSpec((None, tk, d), lambda i, j: (i, 0, 0)),
            pl.BlockSpec((None, tk, d), lambda i, j: (i, 0, 0)),
            pl.BlockSpec((None, bq, d), lambda i, j: (i, j, 0)),
            pl.BlockSpec((None, bq, _LANES), lambda i, j: (i, j, 0)),
            pl.BlockSpec((None, bq, _LANES), lambda i, j: (i, j, 0)),
        ],
        out_specs=pl.BlockSpec((None, bq, d), lambda i, j: (i, j, 0)),
        out_shape=_sds((bh, tq, d), q.dtype, q3),
        interpret=interpret,
    )(q3, k3, v3, do3, lse, delta)

    dkv_kernel = functools.partial(_dkv_kernel, scale=scale, causal=causal,
                                   block_q=bq, block_k=bk, seq_q=tq)
    dk3, dv3 = pl.pallas_call(
        dkv_kernel,
        grid=(bh, tk // bk),
        in_specs=[
            pl.BlockSpec((None, tq, d), lambda i, j: (i, 0, 0)),
            pl.BlockSpec((None, bk, d), lambda i, j: (i, j, 0)),
            pl.BlockSpec((None, bk, d), lambda i, j: (i, j, 0)),
            pl.BlockSpec((None, tq, d), lambda i, j: (i, 0, 0)),
            pl.BlockSpec((None, tq, _LANES), lambda i, j: (i, 0, 0)),
            pl.BlockSpec((None, tq, _LANES), lambda i, j: (i, 0, 0)),
        ],
        out_specs=[
            pl.BlockSpec((None, bk, d), lambda i, j: (i, j, 0)),
            pl.BlockSpec((None, bk, d), lambda i, j: (i, j, 0)),
        ],
        out_shape=[
            _sds((bh, tk, d), k.dtype, q3),
            _sds((bh, tk, d), v.dtype, q3),
        ],
        interpret=interpret,
    )(q3, k3, v3, do3, lse, delta)

    return (_from_bhsd(dq3, b, h), _from_bhsd(dk3, b, h),
            _from_bhsd(dv3, b, h))


flash_attention.defvjp(_flash_fwd_rule, _flash_bwd_rule)
