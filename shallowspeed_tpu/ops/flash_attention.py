"""Flash attention — fused blockwise attention as Pallas TPU kernels.

The hot op of the transformer family (`models/transformer.py`). XLA compiles
the naive `ops.attention` into einsum+softmax+einsum with the full (T, T)
score matrix materialized in HBM; this kernel computes attention blockwise in
VMEM with an online softmax (the FlashAttention-2 formulation), so HBM
traffic is O(T·D) instead of O(T²) and the MXU stays fed from on-chip
memory.

All kernels share one streaming structure: a 3-D grid
(batch·kv-head, out-block, reduction-block) whose INNERMOST axis is the
reduction, so VMEM holds one (block_q, block_k) tile's operands at a time
— per-step VMEM is O(block²), independent of sequence length:

- forward: grid (bh, q-block, k-block); the online-softmax state
  (running max m, normalizer l, unnormalized acc) persists in VMEM
  scratch across the sequential k steps; the output block is normalized
  and the log-sum-exp saved at the last k step.
- backward-dq: grid (bh, q-block, k-block); recomputes p from (q, k,
  lse), forms ds = p * (dp - delta), and accumulates dq = Σ ds·k into a
  revisited f32 output block.
- backward-dkv: grid (bh, k-block, q-block); accumulates dv = Σ pᵀ·do
  and dk = Σ dsᵀ·q the same way.

Every entry point picks between this streaming form and a resident fast
path (whole K/V — or Q/dO/stats for dkv — held in VMEM with a fori_loop
reduction) when the sequence fits `_RESIDENT_BYTES`; the resident form's
causal/window loop bounds skip masked tiles' DMA entirely. In the
streaming form, masked-out tiles skip their COMPUTE with `pl.when`
(whole-tile Mosaic predication) but the grid still visits them.

**Sliding windows** (`window > 0`): position i sees keys
[i - window + 1, i] — identical semantics to `ops.attention`'s
`window=` mask. Out-of-window k-tiles are skipped exactly like causal
future tiles. A long sequence with a small window costs O(T·window).

**Grouped-query attention** is native: pass k/v with fewer heads
(n_kv_heads) than q and the kernels never materialize repeated K/V.
Group folding maps GQA onto the exact same kernel bodies: q's heads
fold as extra ROWS — (B, T, H, D) -> (B·Hkv, G·T, D) with each
G-chunk of rows one query head sharing that kv head — so every q-row
block attends against the SAME resident/streamed K/V tile, which is
precisely the reuse GQA exists to exploit. Kernels recover logical
positions as `row mod T` (blocks never straddle chunks since
block_q | T). MHA is the G=1 special case — one code path.

**Position offsets / ring attention.** Every kernel takes a dynamic
scalar `rel` = (global q position) - (global k position) offset, so the
same kernels compute any DIAGONAL CHUNK of a larger attention problem:
masks compare `rel + local_row >= local_col`. `ring_flash_attention`
builds sequence-parallel ring attention from these chunks — K/V blocks
rotate over the mesh axis with `lax.ppermute` while each device merges
its queries' per-chunk (o, lse) with the standard log-sum-exp chunk
merge, and a hand-written VJP runs the ring again in reverse with the
dk/dv accumulators traveling alongside the K/V blocks. Same contract as
`ops.attention.ring_attention`, but the local compute is this fused
kernel instead of a materialized (T_local, T_local) XLA score matrix.

Wrapped in `jax.custom_vjp`, so `jax.grad` through the transformer uses the
fused backward. On non-TPU backends the kernels run in Pallas interpret mode
(exact same code path, used by the CPU test suite); on TPU they compile via
Mosaic. Layout contract matches `ops.attention`: (batch, seq, heads,
head_dim).

Written per /opt/skills/guides/pallas_guide.md (blockwise VMEM tiling,
online-softmax accumulators, preferred_element_type=f32 on every MXU dot,
@pl.when for edge blocks).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl

_NEG = -1e30  # plain float: jnp scalars would be captured consts in kernels
_LANES = 128  # Mosaic min lane width: row stats (lse/delta) pad to this
# Default kernel tile sizes (auto-shrunk per sequence by _pick_block).
# Exported so out-of-module replay paths (parallel/zb.py's split
# backward) tile identically to every in-module entry point.
DEFAULT_BLOCK_Q = 512
DEFAULT_BLOCK_K = 512


def _interpret_default() -> bool:
    return jax.default_backend() != "tpu"


# Resident-K/V fast path bound: with tk*d at or under this, the whole K and
# V comfortably fit VMEM next to the working blocks, and the single-kernel
# fori_loop formulation avoids the streaming version's per-tile scratch
# round-trips. Above it, stream (VMEM-unbounded). Byte-based (dtype-aware):
# 8k x 64 f32 K/V picks streaming while the same shape in bf16 stays
# resident — an element-count gate let the f32 case overflow the 16MB
# scoped-vmem ceiling by a hair.
_RESIDENT_BYTES = 1 << 20  # 1MB per whole-sequence operand held in VMEM


def _mask(s, qrow, kcol, causal, window):
    """Apply the causal and/or sliding-window mask to a score tile.
    `qrow`/`kcol` are GLOBAL positions (the q side already includes the
    chunk's `rel` offset). Returns (masked scores, mask or None)."""
    valid = None
    if causal:
        valid = qrow >= kcol
    if window > 0:
        wv = kcol > qrow - window
        valid = wv if valid is None else valid & wv
    if valid is not None:
        s = jnp.where(valid, s, _NEG)
    return s, valid


def _kblock_bounds(qstart, block_q, block_k, nkb, causal, window):
    """fori_loop bounds over k-blocks for the q block whose first GLOBAL
    row is `qstart` (resident fwd/dq paths). Tiles outside [lo, hi)
    contain no unmasked entry — their DMA is never issued."""
    lo = jnp.int32(0)
    hi = jnp.int32(nkb)
    if causal:
        hi = jnp.clip((qstart + block_q - 1) // block_k + 1, 0, nkb)
    if window > 0:
        first_col = jnp.maximum(0, qstart - (window - 1))
        lo = jnp.clip(first_col // block_k, 0, nkb)
    return lo, hi


def _tile_live(qstart, jk, block_q, block_k, causal, window):
    """Whether the tile at global-q-start `qstart`, k-block `jk` has any
    unmasked entry (streaming paths' `pl.when` predicate)."""
    live = True
    if causal:  # last q row >= first k col
        live = (qstart + block_q - 1) >= (jk * block_k)
    if window > 0:  # last k col inside the earliest row's window
        wlive = (jk * block_k + block_k - 1) >= (qstart - (window - 1))
        live = wlive if live is True else live & wlive
    return live


# ----------------------------------------------------------------- forward


def _fwd_kernel_resident(q_ref, k_ref, v_ref, o_ref, lse_ref, *, scale,
                         causal, window, rel, block_q, block_k, seq_k,
                         nqb_chunk):
    """Grid (bh, nqb): whole K/V resident in VMEM, fori_loop over k-blocks
    with the online-softmax carry in registers. Fast path for small T."""
    iq = pl.program_id(1)
    iqm = iq % nqb_chunk  # chunk-local block index (GQA row folding)
    qstart = rel + iqm * block_q
    q = q_ref[:].astype(jnp.float32)                       # (bq, D)
    d = q.shape[-1]

    nkb = seq_k // block_k
    lo, hi = _kblock_bounds(qstart, block_q, block_k, nkb, causal, window)

    qrow = qstart + jax.lax.broadcasted_iota(
        jnp.int32, (block_q, block_k), 0)

    def body(j, carry):
        m, l, acc = carry
        kb = k_ref[pl.ds(j * block_k, block_k), :].astype(jnp.float32)
        vb = v_ref[pl.ds(j * block_k, block_k), :].astype(jnp.float32)
        s = jnp.dot(q, kb.T, preferred_element_type=jnp.float32) * scale
        kcol = j * block_k + jax.lax.broadcasted_iota(
            jnp.int32, (block_q, block_k), 1)
        s, valid = _mask(s, qrow, kcol, causal, window)
        m_new = jnp.maximum(m, s.max(axis=-1, keepdims=True))
        p = jnp.exp(s - m_new)
        if valid is not None:
            p = jnp.where(valid, p, 0.0)
        alpha = jnp.exp(m - m_new)
        l_new = l * alpha + p.sum(axis=-1, keepdims=True)
        acc_new = acc * alpha + jnp.dot(
            p, vb, preferred_element_type=jnp.float32)
        return m_new, l_new, acc_new

    m0 = jnp.full((block_q, 1), _NEG)
    l0 = jnp.zeros((block_q, 1), jnp.float32)
    acc0 = jnp.zeros((block_q, d), jnp.float32)
    m, l, acc = jax.lax.fori_loop(lo, hi, body, (m0, l0, acc0))

    o_ref[:] = (acc / jnp.maximum(l, 1e-30)).astype(o_ref.dtype)
    lse_ref[:] = jnp.broadcast_to(
        m + jnp.log(jnp.maximum(l, 1e-30)), (block_q, _LANES))


def _fwd_kernel(q_ref, k_ref, v_ref, o_ref, lse_ref, m_scr, l_scr,
                acc_scr, *, scale, causal, window, rel, block_q,
                block_k, nkb, nqb_chunk):
    """Grid (bh, nqb, nkb) — the K reduction is the INNERMOST grid axis,
    so VMEM holds one (block_q, block_k)-tile's operands at a time; the
    online-softmax state (m, l, acc) lives in scratch that persists
    across the sequential innermost steps, and the (bh, iq) output block
    is finalized at the last K step. Fully-masked causal/window tiles
    skip their matmuls via `pl.when`."""
    iq = pl.program_id(1)
    jk = pl.program_id(2)
    iqm = iq % nqb_chunk
    qstart = rel + iqm * block_q

    @pl.when(jk == 0)
    def _init():
        m_scr[:] = jnp.full_like(m_scr, _NEG)
        l_scr[:] = jnp.zeros_like(l_scr)
        acc_scr[:] = jnp.zeros_like(acc_scr)

    live = _tile_live(qstart, jk, block_q, block_k, causal, window)

    @pl.when(live)
    def _accum():
        q = q_ref[:].astype(jnp.float32)                   # (bq, D)
        kb = k_ref[:].astype(jnp.float32)                  # (bk, D)
        vb = v_ref[:].astype(jnp.float32)
        s = jnp.dot(q, kb.T, preferred_element_type=jnp.float32) * scale
        qrow = qstart + jax.lax.broadcasted_iota(
            jnp.int32, (block_q, block_k), 0)
        kcol = jk * block_k + jax.lax.broadcasted_iota(
            jnp.int32, (block_q, block_k), 1)
        s, valid = _mask(s, qrow, kcol, causal, window)
        m = m_scr[:, 0:1]
        l = l_scr[:, 0:1]
        m_new = jnp.maximum(m, s.max(axis=-1, keepdims=True))
        p = jnp.exp(s - m_new)
        if valid is not None:
            p = jnp.where(valid, p, 0.0)
        alpha = jnp.exp(m - m_new)
        l_new = l * alpha + p.sum(axis=-1, keepdims=True)
        acc_scr[:] = acc_scr[:] * alpha + jnp.dot(
            p, vb, preferred_element_type=jnp.float32)
        m_scr[:] = jnp.broadcast_to(m_new, m_scr.shape)
        l_scr[:] = jnp.broadcast_to(l_new, l_scr.shape)

    @pl.when(jk == nkb - 1)
    def _finalize():
        l = l_scr[:, 0:1]
        o_ref[:] = (acc_scr[:] / jnp.maximum(l, 1e-30)).astype(o_ref.dtype)
        # row stats broadcast across a 128-lane dim (Mosaic min tile width)
        lse_ref[:] = jnp.broadcast_to(
            m_scr[:, 0:1] + jnp.log(jnp.maximum(l, 1e-30)),
            (block_q, _LANES))


# ---------------------------------------------------------------- backward


def _dq_kernel_resident(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
                        dq_ref, *, scale, causal, window, rel, block_q,
                        block_k, seq_k, nqb_chunk):
    """Grid (bh, nqb): whole K/V resident in VMEM, fori_loop over k-blocks
    with shrunk causal/window bounds. Fast path for small T."""
    iq = pl.program_id(1)
    iqm = iq % nqb_chunk
    qstart = rel + iqm * block_q
    q = q_ref[:].astype(jnp.float32)
    do = do_ref[:].astype(jnp.float32)
    lse = lse_ref[:, 0:1]
    delta = delta_ref[:, 0:1]
    d = q.shape[-1]

    nkb = seq_k // block_k
    lo, hi = _kblock_bounds(qstart, block_q, block_k, nkb, causal, window)

    qrow = qstart + jax.lax.broadcasted_iota(
        jnp.int32, (block_q, block_k), 0)

    def body(j, dq):
        kb = k_ref[pl.ds(j * block_k, block_k), :].astype(jnp.float32)
        vb = v_ref[pl.ds(j * block_k, block_k), :].astype(jnp.float32)
        s = jnp.dot(q, kb.T, preferred_element_type=jnp.float32) * scale
        kcol = j * block_k + jax.lax.broadcasted_iota(
            jnp.int32, (block_q, block_k), 1)
        s, _valid = _mask(s, qrow, kcol, causal, window)
        p = jnp.exp(s - lse)
        dp = jnp.dot(do, vb.T, preferred_element_type=jnp.float32)
        ds = p * (dp - delta) * scale
        return dq + jnp.dot(ds, kb, preferred_element_type=jnp.float32)

    dq = jax.lax.fori_loop(
        lo, hi, body, jnp.zeros((block_q, d), jnp.float32))
    dq_ref[:] = dq.astype(dq_ref.dtype)


def _dkv_kernel_resident(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
                         dk_ref, dv_ref, *, scale, causal, window, rel,
                         block_q, block_k, seq_q, nqb_chunk, groups):
    """Grid (bh, nkb): whole Q/dO/stats resident in VMEM; for each of the
    `groups` query-head chunks (GQA row folding; static unroll), a
    fori_loop over that chunk's live q-blocks accumulates into the
    SHARED dk/dv block. Fast path for small T — the stats are
    (T, 128)-lane f32, so this path's VMEM grows 512B/row and is gated
    tighter than the forward's."""
    jk = pl.program_id(1)
    kb = k_ref[:].astype(jnp.float32)                      # (bk, D)
    vb = v_ref[:].astype(jnp.float32)
    d = kb.shape[-1]

    # chunk-local q-block bounds: with global row = rel + local row, a
    # q block is live for this k block iff its last global row reaches
    # the k block (causal) and its first global row is within window
    if causal:
        first = jnp.clip(
            (jk * block_k - rel) // block_q, 0, nqb_chunk)
    else:
        first = jnp.int32(0)
    if window > 0:
        last = jnp.clip(
            (jk * block_k + block_k - 1 + window - 1 - rel) // block_q
            + 1, 0, nqb_chunk)
    else:
        last = jnp.int32(nqb_chunk)

    kcol = jk * block_k + jax.lax.broadcasted_iota(
        jnp.int32, (block_q, block_k), 1)

    def chunk_body(base, carry):
        # `base` = this chunk's first block index in the folded row space
        def body(i, carry):
            dk, dv = carry
            row0 = (base + i) * block_q
            qb = q_ref[pl.ds(row0, block_q), :].astype(jnp.float32)
            dob = do_ref[pl.ds(row0, block_q), :].astype(jnp.float32)
            lse = lse_ref[pl.ds(row0, block_q), 0:1]
            delta = delta_ref[pl.ds(row0, block_q), 0:1]
            s = jnp.dot(qb, kb.T,
                        preferred_element_type=jnp.float32) * scale
            qrow = rel + i * block_q + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 0)
            s, _valid = _mask(s, qrow, kcol, causal, window)
            p = jnp.exp(s - lse)
            dv = dv + jnp.dot(p.T, dob,
                              preferred_element_type=jnp.float32)
            dp = jnp.dot(dob, vb.T, preferred_element_type=jnp.float32)
            ds = p * (dp - delta) * scale
            dk = dk + jnp.dot(ds.T, qb,
                              preferred_element_type=jnp.float32)
            return dk, dv

        return jax.lax.fori_loop(first, last, body, carry)

    dk = jnp.zeros((block_k, d), jnp.float32)
    dv = jnp.zeros((block_k, d), jnp.float32)
    for gi in range(groups):  # static: groups is a compile-time constant
        dk, dv = chunk_body(gi * nqb_chunk, (dk, dv))
    dk_ref[:] = dk.astype(dk_ref.dtype)
    dv_ref[:] = dv.astype(dv_ref.dtype)


def _dq_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref, dq_ref,
               *, scale, causal, window, rel, block_q, block_k,
               nqb_chunk):
    """Grid (bh, nqb, nkb) — the K reduction runs as the INNERMOST grid
    axis so VMEM holds one (block_q, block_k)-tile's operands at a time;
    dq_ref is the (bh, iq) block, revisited across j, f32 accumulated.
    Fully-masked causal/window tiles skip their matmuls via `pl.when`."""
    iq = pl.program_id(1)
    jk = pl.program_id(2)
    iqm = iq % nqb_chunk
    qstart = rel + iqm * block_q

    @pl.when(jk == 0)
    def _init():
        dq_ref[:] = jnp.zeros_like(dq_ref)

    live = _tile_live(qstart, jk, block_q, block_k, causal, window)

    @pl.when(live)
    def _accum():
        q = q_ref[:].astype(jnp.float32)
        do = do_ref[:].astype(jnp.float32)
        lse = lse_ref[:, 0:1]
        delta = delta_ref[:, 0:1]
        kb = k_ref[:].astype(jnp.float32)
        vb = v_ref[:].astype(jnp.float32)
        s = jnp.dot(q, kb.T, preferred_element_type=jnp.float32) * scale
        qrow = qstart + jax.lax.broadcasted_iota(
            jnp.int32, (block_q, block_k), 0)
        kcol = jk * block_k + jax.lax.broadcasted_iota(
            jnp.int32, (block_q, block_k), 1)
        s, _valid = _mask(s, qrow, kcol, causal, window)
        p = jnp.exp(s - lse)
        dp = jnp.dot(do, vb.T, preferred_element_type=jnp.float32)
        ds = p * (dp - delta) * scale
        dq_ref[:] += jnp.dot(ds, kb, preferred_element_type=jnp.float32)


def _dkv_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref, dk_ref,
                dv_ref, *, scale, causal, window, rel, block_q,
                block_k, nqb_chunk):
    """Grid (bh, nkb, nqb_total) — Q reduction innermost (across ALL
    query-group chunks under GQA, so group members' contributions
    accumulate into the shared dk/dv block), (bh, jk) output block
    revisited across i with f32 accumulation; same VMEM story as
    `_dq_kernel`."""
    jk = pl.program_id(1)
    iq = pl.program_id(2)
    iqm = iq % nqb_chunk
    qstart = rel + iqm * block_q

    @pl.when(iq == 0)
    def _init():
        dk_ref[:] = jnp.zeros_like(dk_ref)
        dv_ref[:] = jnp.zeros_like(dv_ref)

    live = _tile_live(qstart, jk, block_q, block_k, causal, window)

    @pl.when(live)
    def _accum():
        kb = k_ref[:].astype(jnp.float32)                  # (bk, D)
        vb = v_ref[:].astype(jnp.float32)
        qb = q_ref[:].astype(jnp.float32)
        dob = do_ref[:].astype(jnp.float32)
        lse = lse_ref[:, 0:1]
        delta = delta_ref[:, 0:1]
        s = jnp.dot(qb, kb.T, preferred_element_type=jnp.float32) * scale
        qrow = qstart + jax.lax.broadcasted_iota(
            jnp.int32, (block_q, block_k), 0)
        kcol = jk * block_k + jax.lax.broadcasted_iota(
            jnp.int32, (block_q, block_k), 1)
        s, _valid = _mask(s, qrow, kcol, causal, window)
        p = jnp.exp(s - lse)
        dv_ref[:] += jnp.dot(p.T, dob, preferred_element_type=jnp.float32)
        dp = jnp.dot(dob, vb.T, preferred_element_type=jnp.float32)
        ds = p * (dp - delta) * scale
        dk_ref[:] += jnp.dot(ds.T, qb, preferred_element_type=jnp.float32)


# ----------------------------------------------------- layout helpers


def _to_bhsd(x):
    """(B, T, H, D) -> (B*H, T, D) for the (batch·head, block) grid."""
    b, t, h, d = x.shape
    return jnp.reshape(jnp.transpose(x, (0, 2, 1, 3)), (b * h, t, d))


def _from_bhsd(x, b, h):
    bh, t, d = x.shape
    return jnp.transpose(jnp.reshape(x, (b, h, t, d)), (0, 2, 1, 3))


def _fold_q(x, kvh):
    """GQA row folding: (B, T, H, D) -> (B*Hkv, G*T, D) where query head
    h = kv*G + g lands in rows [g*T, (g+1)*T) of batch-row b*Hkv + kv —
    each G-chunk of rows is one query head sharing that kv head."""
    b, t, h, d = x.shape
    g = h // kvh
    x = jnp.transpose(x, (0, 2, 1, 3))          # (B, H, T, D)
    return jnp.reshape(x, (b * kvh, g * t, d))  # heads split as (kvh, g)


def _unfold_q(x, b, h):
    """Inverse of `_fold_q`: (B*Hkv, G*T, D) -> (B, T, H, D)."""
    bkv, gt, d = x.shape
    kvh = bkv // b
    g = h // kvh
    x = jnp.reshape(x, (b, kvh, g, gt // g, d))
    x = jnp.reshape(x, (b, h, gt // g, d))
    return jnp.transpose(x, (0, 2, 1, 3))


def _pick_block(t: int, want: int) -> int:
    while t % want:
        want //= 2
    return max(want, 1)


def _sds(shape, dtype, like):
    """ShapeDtypeStruct inheriting `like`'s shard_map variance (vma), so the
    kernels compose with explicit-sharding engines (pallas_call under
    shard_map requires explicit output vma)."""
    vma = getattr(getattr(like, "aval", None), "vma", None)
    if vma:
        return jax.ShapeDtypeStruct(shape, dtype, vma=vma)
    return jax.ShapeDtypeStruct(shape, dtype)


# ------------------------------------------------------------ chunk API
# Folded-space primitives shared by `flash_attention` (rel = 0) and
# `ring_flash_attention` (rel = per-step global offset). All take/return
# (B*Hkv, rows|tk, D) arrays.


def _chunk_fwd(q3, k3, v3, rel, *, causal, window, bq, bk, nqb_chunk,
               interpret, out_dtype=None):
    """One chunk's flash forward. `out_dtype` overrides the o output's
    dtype (default: q3's): the RING path passes f32 so each chunk's
    normalized output reaches the log-sum-exp merge unrounded — with a
    bf16 chunk output every ring hop quantized its partial to bf16
    before the merge, compounding ~sqrt(n_chunks) x the single-rounding
    bf16 floor (the BENCH_r05 `ring_chunk` 2.3x-above-floor finding,
    VERDICT r5 weak #2; BASELINE.md 'ring-chunk numerics envelope').
    The kernel accumulator is f32 either way — this only widens what
    leaves the kernel; single-chunk callers keep the narrow output."""
    bh, rows, d = q3.shape
    tk = k3.shape[1]
    scale = 1.0 / float(np.sqrt(d))
    out_shape = [
        _sds((bh, rows, d), out_dtype or q3.dtype, q3),
        _sds((bh, rows, _LANES), jnp.float32, q3),
    ]
    if tk * d * q3.dtype.itemsize <= _RESIDENT_BYTES:
        kernel = functools.partial(
            _fwd_kernel_resident, scale=scale, causal=causal,
            window=window, rel=rel, block_q=bq, block_k=bk, seq_k=tk,
            nqb_chunk=nqb_chunk)
        return pl.pallas_call(
            kernel,
            grid=(bh, rows // bq),
            in_specs=[
                pl.BlockSpec((None, bq, d), lambda i, j: (i, j, 0)),
                pl.BlockSpec((None, tk, d), lambda i, j: (i, 0, 0)),
                pl.BlockSpec((None, tk, d), lambda i, j: (i, 0, 0)),
            ],
            out_specs=[
                pl.BlockSpec((None, bq, d), lambda i, j: (i, j, 0)),
                pl.BlockSpec((None, bq, _LANES), lambda i, j: (i, j, 0)),
            ],
            out_shape=out_shape,
            interpret=interpret,
        )(q3, k3, v3)
    from jax.experimental.pallas import tpu as pltpu

    kernel = functools.partial(
        _fwd_kernel, scale=scale, causal=causal, window=window, rel=rel,
        block_q=bq, block_k=bk, nkb=tk // bk, nqb_chunk=nqb_chunk)
    return pl.pallas_call(
        kernel,
        grid=(bh, rows // bq, tk // bk),
        in_specs=[
            pl.BlockSpec((None, bq, d), lambda i, j, k_: (i, j, 0)),
            pl.BlockSpec((None, bk, d), lambda i, j, k_: (i, k_, 0)),
            pl.BlockSpec((None, bk, d), lambda i, j, k_: (i, k_, 0)),
        ],
        out_specs=[
            pl.BlockSpec((None, bq, d), lambda i, j, k_: (i, j, 0)),
            pl.BlockSpec((None, bq, _LANES), lambda i, j, k_: (i, j, 0)),
        ],
        out_shape=out_shape,
        scratch_shapes=[
            pltpu.VMEM((bq, _LANES), jnp.float32),  # running max m
            pltpu.VMEM((bq, _LANES), jnp.float32),  # running norm l
            pltpu.VMEM((bq, d), jnp.float32),       # unnormalized out
        ],
        interpret=interpret,
    )(q3, k3, v3)


def _chunk_dq(q3, k3, v3, do3, lse, delta, rel, *, causal, window, bq, bk,
              nqb_chunk, interpret):
    bh, rows, d = q3.shape
    tk = k3.shape[1]
    scale = 1.0 / float(np.sqrt(d))
    if tk * d * q3.dtype.itemsize <= _RESIDENT_BYTES:
        kernel = functools.partial(
            _dq_kernel_resident, scale=scale, causal=causal,
            window=window, rel=rel, block_q=bq, block_k=bk, seq_k=tk,
            nqb_chunk=nqb_chunk)
        return pl.pallas_call(
            kernel,
            grid=(bh, rows // bq),
            in_specs=[
                pl.BlockSpec((None, bq, d), lambda i, j: (i, j, 0)),
                pl.BlockSpec((None, tk, d), lambda i, j: (i, 0, 0)),
                pl.BlockSpec((None, tk, d), lambda i, j: (i, 0, 0)),
                pl.BlockSpec((None, bq, d), lambda i, j: (i, j, 0)),
                pl.BlockSpec((None, bq, _LANES), lambda i, j: (i, j, 0)),
                pl.BlockSpec((None, bq, _LANES), lambda i, j: (i, j, 0)),
            ],
            out_specs=pl.BlockSpec((None, bq, d), lambda i, j: (i, j, 0)),
            out_shape=_sds((bh, rows, d), jnp.float32, q3),
            interpret=interpret,
        )(q3, k3, v3, do3, lse, delta)
    kernel = functools.partial(
        _dq_kernel, scale=scale, causal=causal, window=window, rel=rel,
        block_q=bq, block_k=bk, nqb_chunk=nqb_chunk)
    return pl.pallas_call(
        kernel,
        grid=(bh, rows // bq, tk // bk),
        in_specs=[
            pl.BlockSpec((None, bq, d), lambda i, j, k_: (i, j, 0)),
            pl.BlockSpec((None, bk, d), lambda i, j, k_: (i, k_, 0)),
            pl.BlockSpec((None, bk, d), lambda i, j, k_: (i, k_, 0)),
            pl.BlockSpec((None, bq, d), lambda i, j, k_: (i, j, 0)),
            pl.BlockSpec((None, bq, _LANES), lambda i, j, k_: (i, j, 0)),
            pl.BlockSpec((None, bq, _LANES), lambda i, j, k_: (i, j, 0)),
        ],
        out_specs=pl.BlockSpec((None, bq, d), lambda i, j, k_: (i, j, 0)),
        out_shape=_sds((bh, rows, d), jnp.float32, q3),
        interpret=interpret,
    )(q3, k3, v3, do3, lse, delta)


def _chunk_dkv(q3, k3, v3, do3, lse, delta, rel, *, causal, window, bq,
               bk, nqb_chunk, groups, interpret):
    bh, rows, d = q3.shape
    tk = k3.shape[1]
    scale = 1.0 / float(np.sqrt(d))
    # lse/delta stats are always f32 and get a deliberate 2x allowance;
    # under GQA the WHOLE folded Q/dO/stats must sit in VMEM, so both
    # gates are absolute in `rows`.
    stats_bytes = rows * _LANES * jnp.dtype(jnp.float32).itemsize
    resident = (rows * d * q3.dtype.itemsize <= _RESIDENT_BYTES
                and stats_bytes <= 2 * _RESIDENT_BYTES)
    out_shape = [
        _sds((bh, tk, d), jnp.float32, q3),
        _sds((bh, tk, d), jnp.float32, q3),
    ]
    if resident:
        kernel = functools.partial(
            _dkv_kernel_resident, scale=scale, causal=causal,
            window=window, rel=rel, block_q=bq, block_k=bk,
            seq_q=rows // groups, nqb_chunk=nqb_chunk, groups=groups)
        return pl.pallas_call(
            kernel,
            grid=(bh, tk // bk),
            in_specs=[
                pl.BlockSpec((None, rows, d), lambda i, j: (i, 0, 0)),
                pl.BlockSpec((None, bk, d), lambda i, j: (i, j, 0)),
                pl.BlockSpec((None, bk, d), lambda i, j: (i, j, 0)),
                pl.BlockSpec((None, rows, d), lambda i, j: (i, 0, 0)),
                pl.BlockSpec((None, rows, _LANES), lambda i, j: (i, 0, 0)),
                pl.BlockSpec((None, rows, _LANES), lambda i, j: (i, 0, 0)),
            ],
            out_specs=[
                pl.BlockSpec((None, bk, d), lambda i, j: (i, j, 0)),
                pl.BlockSpec((None, bk, d), lambda i, j: (i, j, 0)),
            ],
            out_shape=out_shape,
            interpret=interpret,
        )(q3, k3, v3, do3, lse, delta)
    kernel = functools.partial(
        _dkv_kernel, scale=scale, causal=causal, window=window, rel=rel,
        block_q=bq, block_k=bk, nqb_chunk=nqb_chunk)
    return pl.pallas_call(
        kernel,
        grid=(bh, tk // bk, rows // bq),
        in_specs=[
            pl.BlockSpec((None, bq, d), lambda i, j, k_: (i, k_, 0)),
            pl.BlockSpec((None, bk, d), lambda i, j, k_: (i, j, 0)),
            pl.BlockSpec((None, bk, d), lambda i, j, k_: (i, j, 0)),
            pl.BlockSpec((None, bq, d), lambda i, j, k_: (i, k_, 0)),
            pl.BlockSpec((None, bq, _LANES), lambda i, j, k_: (i, k_, 0)),
            pl.BlockSpec((None, bq, _LANES), lambda i, j, k_: (i, k_, 0)),
        ],
        out_specs=[
            pl.BlockSpec((None, bk, d), lambda i, j, k_: (i, j, 0)),
            pl.BlockSpec((None, bk, d), lambda i, j, k_: (i, j, 0)),
        ],
        out_shape=out_shape,
        interpret=interpret,
    )(q3, k3, v3, do3, lse, delta)


def _delta_of(do3, o3, like_lse):
    """delta_i = rowsum(dO_i * O_i) — the softmax-jacobian diagonal term,
    broadcast across the 128-lane stats dim like lse."""
    return jnp.broadcast_to(
        jnp.sum(do3.astype(jnp.float32) * o3.astype(jnp.float32),
                axis=-1, keepdims=True),
        like_lse.shape)


# ------------------------------------------------------------- entry points


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6, 7))
def flash_attention(q, k, v, causal: bool = True, window: int = 0,
                    block_q: int = DEFAULT_BLOCK_Q,
                    block_k: int = DEFAULT_BLOCK_K,
                    interpret: bool | None = None):
    """Fused multi-head attention; same contract as `ops.attention`.

    q: (batch, seq, heads, head_dim); k, v: (batch, seq, kv_heads,
    head_dim) with kv_heads | heads — kv_heads < heads is native GQA (no
    repeated K/V is ever materialized). Returns (batch, seq, heads,
    head_dim). `window > 0` restricts position i to keys
    [i - window + 1, i] (sliding-window attention; out-of-window tiles
    are skipped, not just masked). Sequence lengths must be divisible by
    the (auto-shrunk) block sizes.
    `interpret=None` auto-selects Pallas interpret mode off-TPU.
    """
    o, _ = _flash_fwd(q, k, v, causal, window, block_q, block_k, interpret)
    return o


flash_attention.supports_gqa = True
flash_attention.supports_window = True


def _geometry(q, k, block_q, block_k):
    b, tq, h, d = q.shape
    kvh = k.shape[2]
    assert h % kvh == 0, (h, kvh)
    bq = _pick_block(tq, block_q)
    bk = _pick_block(k.shape[1], block_k)
    return b, tq, h, d, kvh, h // kvh, bq, bk, tq // bq


def _flash_fwd(q, k, v, causal, window, block_q, block_k, interpret):
    if interpret is None:
        interpret = _interpret_default()
    b, tq, h, d, kvh, g, bq, bk, nqb_chunk = _geometry(q, k, block_q,
                                                       block_k)
    q3 = _fold_q(q, kvh)                         # (b*kvh, g*tq, d)
    k3, v3 = _to_bhsd(k), _to_bhsd(v)            # (b*kvh, tk, d)
    o3, lse = _chunk_fwd(q3, k3, v3, 0, causal=causal, window=int(window),
                         bq=bq, bk=bk, nqb_chunk=nqb_chunk,
                         interpret=interpret)
    o = _unfold_q(o3, b, h)
    return o, (q, k, v, o, lse)


def _flash_fwd_rule(q, k, v, causal, window, block_q, block_k, interpret):
    o, res = _flash_fwd(q, k, v, causal, window, block_q, block_k,
                        interpret)
    return o, res


def _flash_bwd_rule(causal, window, block_q, block_k, interpret, res, do):
    q, k, v, o, lse = res
    if interpret is None:
        interpret = _interpret_default()
    b, tq, h, d, kvh, g, bq, bk, nqb_chunk = _geometry(q, k, block_q,
                                                       block_k)
    window = int(window)
    q3, k3, v3 = _fold_q(q, kvh), _to_bhsd(k), _to_bhsd(v)
    o3, do3 = _fold_q(o, kvh), _fold_q(do, kvh)
    delta = _delta_of(do3, o3, lse)
    kw = dict(causal=causal, window=window, bq=bq, bk=bk,
              nqb_chunk=nqb_chunk, interpret=interpret)
    dq3 = _chunk_dq(q3, k3, v3, do3, lse, delta, 0, **kw)
    dk3, dv3 = _chunk_dkv(q3, k3, v3, do3, lse, delta, 0, groups=g, **kw)
    return (_unfold_q(dq3, b, h).astype(q.dtype),
            _from_bhsd(dk3, b, kvh).astype(k.dtype),
            _from_bhsd(dv3, b, kvh).astype(v.dtype))


flash_attention.defvjp(_flash_fwd_rule, _flash_bwd_rule)


# ------------------------------------------------------------ ring flash


def _merge_chunks(o_acc, lse_acc, o_i, lse_i):
    """Standard log-sum-exp merge of two normalized attention chunks:
    each o is a softmax-weighted average with total mass exp(lse).
    lse carries the 128-lane stats dim (all lanes identical); the o
    weighting uses lane 0."""
    m = jnp.maximum(lse_acc, lse_i)
    a = jnp.exp(lse_acc - m)                    # (bh, rows, _LANES)
    b = jnp.exp(lse_i - m)
    denom = jnp.maximum(a + b, 1e-30)
    o = (o_acc * a[..., 0:1] + o_i.astype(jnp.float32) * b[..., 0:1]) \
        / denom[..., 0:1]
    return o, m + jnp.log(denom)


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5))
def ring_flash_attention(q, k, v, axis_name: str, causal: bool = True,
                         window: int = 0):
    """Ring attention with the fused flash kernel as the local compute.

    Same contract as `ops.attention.ring_attention` (q: (batch,
    seq_local, heads, head_dim); k/v may carry fewer GQA kv heads; the
    global sequence is the concatenation of blocks in mesh-axis order),
    but each ring step runs the blockwise Pallas kernel on its
    (local q) x (visiting K/V block) chunk — masks offset by the chunk's
    global position delta, out-of-range tiles skipped — instead of
    materializing a (T_local, T_local) XLA score matrix. Per-chunk
    (o, lse) merge with the standard log-sum-exp rule; the hand-written
    VJP rides the ring in reverse with dk/dv accumulators traveling
    alongside the K/V blocks (each block collects its gradient from
    every query shard exactly once, then arrives home)."""
    o, _ = _ring_fwd(q, k, v, axis_name, causal, window)
    return o


ring_flash_attention.supports_gqa = True
ring_flash_attention.supports_window = True


def _ring_geometry(q, k):
    b, t, h, d = q.shape
    kvh = k.shape[2]
    assert h % kvh == 0, (h, kvh)
    bq = _pick_block(t, DEFAULT_BLOCK_Q)
    bk = _pick_block(k.shape[1], DEFAULT_BLOCK_K)
    return b, t, h, d, kvh, h // kvh, bq, bk, t // bq


def _ring_fwd(q, k, v, axis_name, causal, window):
    from jax import lax

    interpret = _interpret_default()
    n = lax.psum(1, axis_name)
    idx = lax.axis_index(axis_name)
    b, t, h, d, kvh, g, bq, bk, nqb_chunk = _ring_geometry(q, k)
    window = int(window)
    q3 = _fold_q(q, kvh)
    k3, v3 = _to_bhsd(k), _to_bhsd(v)
    # f32 chunk outputs: the lse-merge carry is f32, so a bf16 chunk
    # output would round every partial once per ring hop before
    # merging (see _chunk_fwd's out_dtype note)
    kw = dict(causal=causal, window=window, bq=bq, bk=bk,
              nqb_chunk=nqb_chunk, interpret=interpret,
              out_dtype=jnp.float32)
    perm = [(j, (j + 1) % n) for j in range(n)]

    # Ring step i: device idx holds the K/V block of device (idx - i)
    # mod n, so the position offset rel = (global q start) - (global k
    # start) is i*t when idx >= i and (i-n)*t otherwise. The step count
    # n is STATIC (mesh axis size), so the ring unrolls as a Python loop
    # and each chunk gets a COMPILE-TIME rel — kernels stay free of
    # dynamic scalars, and under causal masking the idx < i branch
    # (q entirely before the visiting block) skips its kernels outright.
    zq = q3.astype(jnp.float32).sum() * 0.0
    o3 = jnp.zeros(q3.shape, jnp.float32) + zq
    lse = jnp.full((q3.shape[0], q3.shape[1], _LANES), _NEG) + zq
    kb, vb = k3, v3
    for i in range(n):
        if i == 0:
            o3, lse = _merge_chunks(o3, lse, *_chunk_fwd(q3, kb, vb, 0,
                                                         **kw))
        elif causal and window == 0:
            # future block on idx < i: fully masked — skip the kernel
            def live(ops, i=i):
                return _merge_chunks(ops[0], ops[1], *_chunk_fwd(
                    q3, ops[2], ops[3], i * t, **kw))

            o3, lse = lax.cond(idx >= i, live,
                               lambda ops: (ops[0], ops[1]),
                               (o3, lse, kb, vb))
        else:
            def fwd_at(rel):
                def f(ops):
                    return _merge_chunks(ops[0], ops[1], *_chunk_fwd(
                        q3, ops[2], ops[3], rel, **kw))

                return f

            o3, lse = lax.cond(idx >= i, fwd_at(i * t),
                               fwd_at((i - n) * t), (o3, lse, kb, vb))
        if i + 1 < n:
            kb = lax.ppermute(kb, axis_name, perm)
            vb = lax.ppermute(vb, axis_name, perm)
    o = _unfold_q(o3.astype(q.dtype), b, h)
    return o, (q, k, v, _unfold_q(o3, b, h), lse)


def _ring_fwd_rule(q, k, v, axis_name, causal, window):
    o, res = _ring_fwd(q, k, v, axis_name, causal, window)
    return o, res


def _ring_bwd_rule(axis_name, causal, window, res, do):
    from jax import lax

    q, k, v, o_f32, lse = res
    interpret = _interpret_default()
    n = lax.psum(1, axis_name)
    idx = lax.axis_index(axis_name)
    b, t, h, d, kvh, g, bq, bk, nqb_chunk = _ring_geometry(q, k)
    window = int(window)
    q3, k3, v3 = _fold_q(q, kvh), _to_bhsd(k), _to_bhsd(v)
    o3, do3 = _fold_q(o_f32, kvh), _fold_q(do, kvh)
    delta = _delta_of(do3, o3, lse)
    kw = dict(causal=causal, window=window, bq=bq, bk=bk,
              nqb_chunk=nqb_chunk, interpret=interpret)
    perm = [(j, (j + 1) % n) for j in range(n)]

    # Reverse ring, same static-rel unrolling as the forward. dk/dv
    # accumulators travel WITH their K/V block (rotated together every
    # hop): after n hops each block is home, having collected its
    # gradient contribution from every query shard exactly once.
    zq = q3.astype(jnp.float32).sum() * 0.0
    dq3 = jnp.zeros(q3.shape, jnp.float32) + zq
    dkb = jnp.zeros(k3.shape, jnp.float32) + zq
    dvb = jnp.zeros(k3.shape, jnp.float32) + zq
    kb, vb = k3, v3

    def contrib_at(rel):
        def f(ops):
            dq, dkb, dvb, kb, vb = ops
            dq = dq + _chunk_dq(q3, kb, vb, do3, lse, delta, rel, **kw)
            dk_i, dv_i = _chunk_dkv(q3, kb, vb, do3, lse, delta, rel,
                                    groups=g, **kw)
            return dq, dkb + dk_i, dvb + dv_i

        return f

    for i in range(n):
        ops = (dq3, dkb, dvb, kb, vb)
        if i == 0:
            dq3, dkb, dvb = contrib_at(0)(ops)
        elif causal and window == 0:
            dq3, dkb, dvb = lax.cond(
                idx >= i, contrib_at(i * t),
                lambda ops: (ops[0], ops[1], ops[2]), ops)
        else:
            dq3, dkb, dvb = lax.cond(
                idx >= i, contrib_at(i * t), contrib_at((i - n) * t),
                ops)
        # rotate grads with their block; the LAST hop brings every
        # block's accumulator home (unlike the fwd, this hop is needed)
        dkb = lax.ppermute(dkb, axis_name, perm)
        dvb = lax.ppermute(dvb, axis_name, perm)
        kb = lax.ppermute(kb, axis_name, perm)
        vb = lax.ppermute(vb, axis_name, perm)
    return (_unfold_q(dq3, b, h).astype(q.dtype),
            _from_bhsd(dkb, b, kvh).astype(k.dtype),
            _from_bhsd(dvb, b, kvh).astype(v.dtype))


ring_flash_attention.defvjp(_ring_fwd_rule, _ring_bwd_rule)


# ------------------------------------------------------ paged flash decode
#
# Single-query attention for the serving runtime's paged KV cache
# (round 14, ROADMAP item 1). The XLA reference path
# (`serving/cache.gather_table` + `kv_cache.masked_attention`) first
# MATERIALIZES each row's gathered table — a contiguous
# (rows, Hkv, W*bs, hd) copy of every live block — and then attends
# over it: the hot decode tick pays the cache sweep twice (gather
# write + attention read). This kernel grids DIRECTLY over the block
# table instead — grid (slot, kv head, table column), with the table
# and each row's position as SCALAR-PREFETCH operands so the k/v
# BlockSpec index maps dereference `bt[slot, col]` and DMA exactly the
# pool block each program needs. The gather disappears from the hot
# path; online-softmax scratch merges the per-block partials across
# the innermost table-column axis (same (m, l, acc) carry as the
# training kernels above).
#
# int8 pools are read NATIVELY: the int8 k/v blocks and their f32
# scale planes stream into VMEM as stored, K's per-position scale
# multiplies the score row and V's folds into the probability row —
# the same outside-the-dot placement as `masked_attention`, so HBM
# reads stay 1 byte/element and the reference parity is fp-reorder
# noise only (pinned <= 1e-4 in tests/test_serving.py; compiled-mode
# envelope recorded in bench.py's kernel_numerics_rel_err block).


def _paged_decode_kernel(bt_ref, pos_ref, *refs, scale, bs, w, window,
                         groups, quant):
    """Grid (slot, kv head, table col). One program attends this
    slot's query group against ONE pool block of its table; scratch
    carries the online softmax across the sequential col axis. With
    `quant`, the int8 k/v blocks arrive as stored and their f32 scale
    planes ride as separate (bs, 1) operands — the DMA reads stay
    1 byte/element."""
    if quant:
        (q_ref, k_ref, ks_ref, v_ref, vs_ref, o_ref,
         m_scr, l_scr, acc_scr) = refs
    else:
        q_ref, k_ref, v_ref, o_ref, m_scr, l_scr, acc_scr = refs
    s = pl.program_id(0)
    jw = pl.program_id(2)

    @pl.when(jw == 0)
    def _init():
        m_scr[...] = jnp.full_like(m_scr, _NEG)
        l_scr[...] = jnp.zeros_like(l_scr)
        acc_scr[...] = jnp.zeros_like(acc_scr)

    p = pos_ref[s]
    base = jw * bs
    # tiles whose whole block is masked (beyond this row's position, or
    # before its window) skip compute AND their stats update; their DMA
    # still lands — the table is data, so the grid cannot shrink per
    # row — but scratch carries the merge past them unchanged
    live = base <= p
    if window > 0:
        live = jnp.logical_and(live, base + bs - 1 > p - window)

    @pl.when(live)
    def _accum():
        q = q_ref[0].astype(jnp.float32)                   # (G, hd)
        kb = k_ref[0].astype(jnp.float32)                  # (bs, hd)
        vb = v_ref[0].astype(jnp.float32)
        if quant:
            ks = ks_ref[0, :, 0].astype(jnp.float32)       # (bs,)
            vs = vs_ref[0, :, 0].astype(jnp.float32)
        sc = jnp.dot(q, kb.T, preferred_element_type=jnp.float32)
        if quant:
            sc = sc * ks[None, :]
        sc = sc * scale
        col = base + jax.lax.broadcasted_iota(
            jnp.int32, (groups, bs), 1)
        valid = col <= p
        if window > 0:
            valid = valid & (col > p - window)
        sc = jnp.where(valid, sc, _NEG)
        m = m_scr[:, 0:1]
        l = l_scr[:, 0:1]
        m_new = jnp.maximum(m, sc.max(axis=-1, keepdims=True))
        pr = jnp.where(valid, jnp.exp(sc - m_new), 0.0)
        alpha = jnp.exp(m - m_new)
        l_new = l * alpha + pr.sum(axis=-1, keepdims=True)
        if quant:  # V's scale folds into the probability row (tiny),
            #        keeping the V read int8 — masked_attention's rule;
            #        the normalizer l is accumulated UNSCALED above
            pr = pr * vs[None, :]
        acc_scr[...] = acc_scr[...] * alpha + jnp.dot(
            pr, vb, preferred_element_type=jnp.float32)
        m_scr[...] = jnp.broadcast_to(m_new, m_scr.shape)
        l_scr[...] = jnp.broadcast_to(l_new, l_scr.shape)

    @pl.when(jw == w - 1)
    def _finalize():
        l = l_scr[:, 0:1]
        o_ref[0] = (acc_scr[...]
                    / jnp.maximum(l, 1e-30)).astype(o_ref.dtype)


def paged_flash_decode(q, pool_blk, bt, pos, *, window: int = 0,
                       interpret: bool | None = None):
    """Single-token attention through a paged block table, fused.

    q: (S, H, hd) — one query token per slot; pool_blk: one layer's
    pools {"k"/"v": (N, Hkv, bs, hd)[, "k_s"/"v_s": (N, Hkv, bs, 1)
    f32 scales — int8 pools]}; bt: (S, W) int32 block tables (padding
    columns point at the scratch block); pos: (S,) int32 — each slot's
    current position (valid cache span is [0, pos], optionally
    windowed). Returns (S, H, hd) in q's dtype.

    Matches `masked_attention(q, gather_table(pool, bt), valid)` — the
    XLA reference that stays in `serving/cache.py` — to fp-reorder
    noise (<= 1e-4 pinned): same f32 score/softmax path, same
    outside-the-dot int8 scale placement, no gathered copy. GQA is
    native (H = G * Hkv query heads fold into the program's row axis).
    """
    if interpret is None:
        interpret = _interpret_default()
    from jax.experimental.pallas import tpu as pltpu

    s, h, hd = q.shape
    kp, vp = pool_blk["k"], pool_blk["v"]
    n, hkv, bs, _ = kp.shape
    assert h % hkv == 0, (h, hkv)
    g = h // hkv
    w = bt.shape[1]
    quant = "k_s" in pool_blk
    scale = 1.0 / float(np.sqrt(hd))
    q4 = q.reshape(s, hkv, g, hd)
    kernel = functools.partial(
        _paged_decode_kernel, scale=scale, bs=bs, w=w,
        window=int(window), groups=g, quant=quant)

    def _deref(i, j, k_, bt_ref, pos_ref):
        # the paged gather, moved into the index map: each program's
        # k/v (and scale-plane) DMA fetches the pool block its table
        # column names — no contiguous gathered copy is ever built
        return (bt_ref[i, k_], j, 0, 0)

    qspec = pl.BlockSpec((1, None, g, hd),
                         lambda i, j, k_, bt_ref, pos_ref: (i, j, 0, 0))
    blkspec = pl.BlockSpec((1, None, bs, hd), _deref)
    sclspec = pl.BlockSpec((1, None, bs, 1), _deref)
    if quant:
        in_specs = [qspec, blkspec, sclspec, blkspec, sclspec]
        operands = (q4, kp, pool_blk["k_s"], vp, pool_blk["v_s"])
    else:
        in_specs = [qspec, blkspec, blkspec]
        operands = (q4, kp, vp)
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=2,
        grid=(s, hkv, w),
        in_specs=in_specs,
        out_specs=pl.BlockSpec((1, None, g, hd),
                               lambda i, j, k_, bt_ref, pos_ref:
                               (i, j, 0, 0)),
        scratch_shapes=[
            pltpu.VMEM((g, _LANES), jnp.float32),  # running max m
            pltpu.VMEM((g, _LANES), jnp.float32),  # running norm l
            pltpu.VMEM((g, hd), jnp.float32),      # unnormalized out
        ],
    )
    out = pl.pallas_call(
        kernel,
        grid_spec=grid_spec,
        out_shape=_sds((s, hkv, g, hd), q.dtype, q),
        interpret=interpret,
    )(bt, pos, *operands)
    return out.reshape(s, h, hd)


paged_flash_decode.supports_gqa = True
paged_flash_decode.supports_window = True
