"""Flash attention — fused blockwise attention as Pallas TPU kernels.

The hot op of the transformer family (`models/transformer.py`). XLA compiles
the naive `ops.attention` into einsum+softmax+einsum with the full (T, T)
score matrix materialized in HBM; this kernel computes attention blockwise in
VMEM with an online softmax (the FlashAttention-2 formulation), so HBM
traffic is O(T·D) instead of O(T²) and the MXU stays fed from on-chip
memory. Three kernels:

All three kernels share one streaming structure: a 3-D grid
(batch·head, out-block, reduction-block) whose INNERMOST axis is the
reduction, so VMEM holds one (block_q, block_k) tile's operands at a time
— per-step VMEM is O(block²), independent of sequence length:

- forward: grid (bh, q-block, k-block); the online-softmax state
  (running max m, normalizer l, unnormalized acc) persists in VMEM
  scratch across the sequential k steps; the output block is normalized
  and the log-sum-exp saved at the last k step.
- backward-dq: grid (bh, q-block, k-block); recomputes p from (q, k,
  lse), forms ds = p * (dp - delta), and accumulates dq = Σ ds·k into a
  revisited f32 output block.
- backward-dkv: grid (bh, k-block, q-block); accumulates dv = Σ pᵀ·do
  and dk = Σ dsᵀ·q the same way.

Every entry point picks between this streaming form and a resident fast
path (whole K/V — or Q/dO/stats for dkv — held in VMEM with a fori_loop
reduction) when the sequence fits `_RESIDENT_BYTES`; resident is ~10%
faster at T=8k (no per-tile scratch round-trips) and its causal loop
bounds skip masked tiles' DMA entirely. In the streaming form, causal
masking drops fully-masked tiles' COMPUTE with `pl.when` (whole-tile
Mosaic predication) but the grid still visits them, so their block DMA
traffic is not saved — the FLOP savings of the old loop bounds are kept,
the bandwidth savings only on the resident path.

Wrapped in `jax.custom_vjp`, so `jax.grad` through the transformer uses the
fused backward. On non-TPU backends the kernels run in Pallas interpret mode
(exact same code path, used by the CPU test suite); on TPU they compile via
Mosaic. Layout contract matches `ops.attention`: (batch, seq, heads,
head_dim).

Written per /opt/skills/guides/pallas_guide.md (blockwise VMEM tiling,
online-softmax accumulators, preferred_element_type=f32 on every MXU dot,
@pl.when for edge blocks).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl

_NEG = -1e30  # plain float: jnp scalars would be captured consts in kernels
_LANES = 128  # Mosaic min lane width: row stats (lse/delta) pad to this


def _interpret_default() -> bool:
    return jax.default_backend() != "tpu"


# ----------------------------------------------------------------- forward


# Resident-K/V fast path bound: with tk*d at or under this, the whole K and
# V comfortably fit VMEM next to the working blocks, and the single-kernel
# fori_loop formulation avoids the streaming version's per-tile scratch
# round-trips (~10% at T=8k measured). Above it, stream (VMEM-unbounded).
# Byte-based (dtype-aware): 8k x 64 f32 K/V picks streaming while the same
# shape in bf16 stays resident — an element-count gate let the f32 case
# overflow the 16MB scoped-vmem ceiling by a hair.
_RESIDENT_BYTES = 1 << 20  # 1MB per whole-sequence operand held in VMEM


def _fwd_kernel_resident(q_ref, k_ref, v_ref, o_ref, lse_ref, *, scale,
                         causal, block_q, block_k, seq_k):
    """Grid (bh, nqb): whole K/V resident in VMEM, fori_loop over k-blocks
    with the online-softmax carry in registers. Fast path for small T."""
    iq = pl.program_id(1)
    q = q_ref[:].astype(jnp.float32)                       # (bq, D)
    d = q.shape[-1]

    nkb = seq_k // block_k
    if causal:
        # q rows of this block end at global row iq*bq + bq - 1; k blocks
        # strictly past that are fully masked — shrink the loop bound.
        last = (iq * block_q + block_q - 1) // block_k
        nkb_eff = jnp.minimum(nkb, last + 1)
    else:
        nkb_eff = nkb

    qrow = iq * block_q + jax.lax.broadcasted_iota(
        jnp.int32, (block_q, block_k), 0)

    def body(j, carry):
        m, l, acc = carry
        kb = k_ref[pl.ds(j * block_k, block_k), :].astype(jnp.float32)
        vb = v_ref[pl.ds(j * block_k, block_k), :].astype(jnp.float32)
        s = jnp.dot(q, kb.T, preferred_element_type=jnp.float32) * scale
        if causal:
            kcol = j * block_k + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 1)
            valid = qrow >= kcol
            s = jnp.where(valid, s, _NEG)
        m_new = jnp.maximum(m, s.max(axis=-1, keepdims=True))
        p = jnp.exp(s - m_new)
        if causal:
            p = jnp.where(valid, p, 0.0)
        alpha = jnp.exp(m - m_new)
        l_new = l * alpha + p.sum(axis=-1, keepdims=True)
        acc_new = acc * alpha + jnp.dot(
            p, vb, preferred_element_type=jnp.float32)
        return m_new, l_new, acc_new

    m0 = jnp.full((block_q, 1), _NEG)
    l0 = jnp.zeros((block_q, 1), jnp.float32)
    acc0 = jnp.zeros((block_q, d), jnp.float32)
    m, l, acc = jax.lax.fori_loop(0, nkb_eff, body, (m0, l0, acc0))

    o_ref[:] = (acc / jnp.maximum(l, 1e-30)).astype(o_ref.dtype)
    lse_ref[:] = jnp.broadcast_to(
        m + jnp.log(jnp.maximum(l, 1e-30)), (block_q, _LANES))


def _fwd_kernel(q_ref, k_ref, v_ref, o_ref, lse_ref, m_scr, l_scr, acc_scr,
                *, scale, causal, block_q, block_k, nkb):
    """Grid (bh, nqb, nkb) — the K reduction is the INNERMOST grid axis,
    so VMEM holds one (block_q, block_k)-tile's operands at a time; the
    online-softmax state (m, l, acc) lives in scratch that persists
    across the sequential innermost steps, and the (bh, iq) output block
    is finalized at the last K step. Fully-masked causal tiles skip their
    matmuls via `pl.when` (replacing the old shrunk fori_loop bound)."""
    iq = pl.program_id(1)
    jk = pl.program_id(2)

    @pl.when(jk == 0)
    def _init():
        m_scr[:] = jnp.full_like(m_scr, _NEG)
        l_scr[:] = jnp.zeros_like(l_scr)
        acc_scr[:] = jnp.zeros_like(acc_scr)

    live = True
    if causal:  # tile with no unmasked entry: last q row < first k col
        live = (iq * block_q + block_q - 1) >= (jk * block_k)

    @pl.when(live)
    def _accum():
        q = q_ref[:].astype(jnp.float32)                   # (bq, D)
        kb = k_ref[:].astype(jnp.float32)                  # (bk, D)
        vb = v_ref[:].astype(jnp.float32)
        s = jnp.dot(q, kb.T, preferred_element_type=jnp.float32) * scale
        if causal:
            qrow = iq * block_q + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 0)
            kcol = jk * block_k + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 1)
            valid = qrow >= kcol
            s = jnp.where(valid, s, _NEG)
        m = m_scr[:, 0:1]
        l = l_scr[:, 0:1]
        m_new = jnp.maximum(m, s.max(axis=-1, keepdims=True))
        p = jnp.exp(s - m_new)
        if causal:
            p = jnp.where(valid, p, 0.0)
        alpha = jnp.exp(m - m_new)
        l_new = l * alpha + p.sum(axis=-1, keepdims=True)
        acc_scr[:] = acc_scr[:] * alpha + jnp.dot(
            p, vb, preferred_element_type=jnp.float32)
        m_scr[:] = jnp.broadcast_to(m_new, m_scr.shape)
        l_scr[:] = jnp.broadcast_to(l_new, l_scr.shape)

    @pl.when(jk == nkb - 1)
    def _finalize():
        l = l_scr[:, 0:1]
        o_ref[:] = (acc_scr[:] / jnp.maximum(l, 1e-30)).astype(o_ref.dtype)
        # row stats broadcast across a 128-lane dim (Mosaic min tile width)
        lse_ref[:] = jnp.broadcast_to(
            m_scr[:, 0:1] + jnp.log(jnp.maximum(l, 1e-30)),
            (block_q, _LANES))


# ---------------------------------------------------------------- backward


def _dq_kernel_resident(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
                        dq_ref, *, scale, causal, block_q, block_k, seq_k):
    """Grid (bh, nqb): whole K/V resident in VMEM, fori_loop over k-blocks
    with a shrunk causal bound. Fast path for small T."""
    iq = pl.program_id(1)
    q = q_ref[:].astype(jnp.float32)
    do = do_ref[:].astype(jnp.float32)
    lse = lse_ref[:, 0:1]
    delta = delta_ref[:, 0:1]
    d = q.shape[-1]

    nkb = seq_k // block_k
    if causal:
        last = (iq * block_q + block_q - 1) // block_k
        nkb_eff = jnp.minimum(nkb, last + 1)
    else:
        nkb_eff = nkb

    qrow = iq * block_q + jax.lax.broadcasted_iota(
        jnp.int32, (block_q, block_k), 0)

    def body(j, dq):
        kb = k_ref[pl.ds(j * block_k, block_k), :].astype(jnp.float32)
        vb = v_ref[pl.ds(j * block_k, block_k), :].astype(jnp.float32)
        s = jnp.dot(q, kb.T, preferred_element_type=jnp.float32) * scale
        if causal:
            kcol = j * block_k + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 1)
            s = jnp.where(qrow >= kcol, s, _NEG)
        p = jnp.exp(s - lse)
        dp = jnp.dot(do, vb.T, preferred_element_type=jnp.float32)
        ds = p * (dp - delta) * scale
        return dq + jnp.dot(ds, kb, preferred_element_type=jnp.float32)

    dq = jax.lax.fori_loop(
        0, nkb_eff, body, jnp.zeros((block_q, d), jnp.float32))
    dq_ref[:] = dq.astype(dq_ref.dtype)


def _dkv_kernel_resident(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
                         dk_ref, dv_ref, *, scale, causal, block_q,
                         block_k, seq_q):
    """Grid (bh, nkb): whole Q/dO/stats resident in VMEM, fori_loop from
    the first live q-block. Fast path for small T — the stats are
    (T, 128)-lane f32, so this path's VMEM grows 512B/row and is gated
    tighter than the forward's."""
    jk = pl.program_id(1)
    kb = k_ref[:].astype(jnp.float32)                      # (bk, D)
    vb = v_ref[:].astype(jnp.float32)
    d = kb.shape[-1]

    nqb = seq_q // block_q
    if causal:
        # q blocks strictly before this k block are fully masked
        first = (jk * block_k) // block_q
    else:
        first = 0

    kcol = jk * block_k + jax.lax.broadcasted_iota(
        jnp.int32, (block_q, block_k), 1)

    def body(i, carry):
        dk, dv = carry
        qb = q_ref[pl.ds(i * block_q, block_q), :].astype(jnp.float32)
        dob = do_ref[pl.ds(i * block_q, block_q), :].astype(jnp.float32)
        lse = lse_ref[pl.ds(i * block_q, block_q), 0:1]
        delta = delta_ref[pl.ds(i * block_q, block_q), 0:1]
        s = jnp.dot(qb, kb.T, preferred_element_type=jnp.float32) * scale
        if causal:
            qrow = i * block_q + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 0)
            s = jnp.where(qrow >= kcol, s, _NEG)
        p = jnp.exp(s - lse)
        dv = dv + jnp.dot(p.T, dob, preferred_element_type=jnp.float32)
        dp = jnp.dot(dob, vb.T, preferred_element_type=jnp.float32)
        ds = p * (dp - delta) * scale
        dk = dk + jnp.dot(ds.T, qb, preferred_element_type=jnp.float32)
        return dk, dv

    dk0 = jnp.zeros((block_k, d), jnp.float32)
    dv0 = jnp.zeros((block_k, d), jnp.float32)
    dk, dv = jax.lax.fori_loop(first, nqb, body, (dk0, dv0))
    dk_ref[:] = dk.astype(dk_ref.dtype)
    dv_ref[:] = dv.astype(dv_ref.dtype)


def _dq_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref, dq_ref, *,
               scale, causal, block_q, block_k):
    """Grid (bh, nqb, nkb) — the K reduction runs as the INNERMOST grid
    axis so VMEM holds one (block_q, block_k)-tile's operands at a time
    (the previous whole-sequence block specs hit the scoped-vmem ceiling
    at T≈8k); dq_ref is the (bh, iq) block, revisited across j, f32
    accumulated. Fully-masked causal tiles skip their matmuls via
    `pl.when` (Mosaic predication), preserving the old loop-bound
    optimization."""
    iq = pl.program_id(1)
    jk = pl.program_id(2)

    @pl.when(jk == 0)
    def _init():
        dq_ref[:] = jnp.zeros_like(dq_ref)

    live = True
    if causal:  # tile with no unmasked entry: last q row < first k col
        live = (iq * block_q + block_q - 1) >= (jk * block_k)

    @pl.when(live)
    def _accum():
        q = q_ref[:].astype(jnp.float32)
        do = do_ref[:].astype(jnp.float32)
        lse = lse_ref[:, 0:1]
        delta = delta_ref[:, 0:1]
        kb = k_ref[:].astype(jnp.float32)
        vb = v_ref[:].astype(jnp.float32)
        s = jnp.dot(q, kb.T, preferred_element_type=jnp.float32) * scale
        if causal:
            qrow = iq * block_q + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 0)
            kcol = jk * block_k + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 1)
            s = jnp.where(qrow >= kcol, s, _NEG)
        p = jnp.exp(s - lse)
        dp = jnp.dot(do, vb.T, preferred_element_type=jnp.float32)
        ds = p * (dp - delta) * scale
        dq_ref[:] += jnp.dot(ds, kb, preferred_element_type=jnp.float32)


def _dkv_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
                dk_ref, dv_ref, *, scale, causal, block_q, block_k):
    """Grid (bh, nkb, nqb) — Q reduction innermost, (bh, jk) output block
    revisited across i with f32 accumulation; same VMEM story as
    `_dq_kernel`."""
    jk = pl.program_id(1)
    iq = pl.program_id(2)

    @pl.when(iq == 0)
    def _init():
        dk_ref[:] = jnp.zeros_like(dk_ref)
        dv_ref[:] = jnp.zeros_like(dv_ref)

    live = True
    if causal:
        live = (iq * block_q + block_q - 1) >= (jk * block_k)

    @pl.when(live)
    def _accum():
        kb = k_ref[:].astype(jnp.float32)                  # (bk, D)
        vb = v_ref[:].astype(jnp.float32)
        qb = q_ref[:].astype(jnp.float32)
        dob = do_ref[:].astype(jnp.float32)
        lse = lse_ref[:, 0:1]
        delta = delta_ref[:, 0:1]
        s = jnp.dot(qb, kb.T, preferred_element_type=jnp.float32) * scale
        if causal:
            qrow = iq * block_q + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 0)
            kcol = jk * block_k + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 1)
            s = jnp.where(qrow >= kcol, s, _NEG)
        p = jnp.exp(s - lse)
        dv_ref[:] += jnp.dot(p.T, dob, preferred_element_type=jnp.float32)
        dp = jnp.dot(dob, vb.T, preferred_element_type=jnp.float32)
        ds = p * (dp - delta) * scale
        dk_ref[:] += jnp.dot(ds.T, qb, preferred_element_type=jnp.float32)


# ------------------------------------------------------------- entry points


def _to_bhsd(x):
    """(B, T, H, D) -> (B*H, T, D) for the (batch·head, block) grid."""
    b, t, h, d = x.shape
    return jnp.reshape(jnp.transpose(x, (0, 2, 1, 3)), (b * h, t, d))


def _from_bhsd(x, b, h):
    bh, t, d = x.shape
    return jnp.transpose(jnp.reshape(x, (b, h, t, d)), (0, 2, 1, 3))


def _pick_block(t: int, want: int) -> int:
    while t % want:
        want //= 2
    return max(want, 1)


def _sds(shape, dtype, like):
    """ShapeDtypeStruct inheriting `like`'s shard_map variance (vma), so the
    kernels compose with explicit-sharding engines (pallas_call under
    shard_map requires explicit output vma)."""
    vma = getattr(getattr(like, "aval", None), "vma", None)
    if vma:
        return jax.ShapeDtypeStruct(shape, dtype, vma=vma)
    return jax.ShapeDtypeStruct(shape, dtype)


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6))
def flash_attention(q, k, v, causal: bool = True, block_q: int = 128,
                    block_k: int = 128, interpret: bool | None = None):
    """Fused multi-head attention; same contract as `ops.attention`.

    q, k, v: (batch, seq, heads, head_dim) -> (batch, seq, heads, head_dim).
    Sequence lengths must be divisible by the (auto-shrunk) block sizes.
    `interpret=None` auto-selects Pallas interpret mode off-TPU.
    """
    o, _ = _flash_fwd(q, k, v, causal, block_q, block_k, interpret)
    return o


def _flash_fwd(q, k, v, causal, block_q, block_k, interpret):
    if interpret is None:
        interpret = _interpret_default()
    b, tq, h, d = q.shape
    tk = k.shape[1]
    bq = _pick_block(tq, block_q)
    bk = _pick_block(tk, block_k)
    scale = 1.0 / float(np.sqrt(d))

    q3, k3, v3 = _to_bhsd(q), _to_bhsd(k), _to_bhsd(v)
    bh = b * h

    out_shape = [
        _sds((bh, tq, d), q.dtype, q3),
        _sds((bh, tq, _LANES), jnp.float32, q3),
    ]
    if tk * d * q.dtype.itemsize <= _RESIDENT_BYTES:
        kernel = functools.partial(
            _fwd_kernel_resident, scale=scale, causal=causal, block_q=bq,
            block_k=bk, seq_k=tk)
        o3, lse = pl.pallas_call(
            kernel,
            grid=(bh, tq // bq),
            in_specs=[
                pl.BlockSpec((None, bq, d), lambda i, j: (i, j, 0)),
                pl.BlockSpec((None, tk, d), lambda i, j: (i, 0, 0)),
                pl.BlockSpec((None, tk, d), lambda i, j: (i, 0, 0)),
            ],
            out_specs=[
                pl.BlockSpec((None, bq, d), lambda i, j: (i, j, 0)),
                pl.BlockSpec((None, bq, _LANES), lambda i, j: (i, j, 0)),
            ],
            out_shape=out_shape,
            interpret=interpret,
        )(q3, k3, v3)
    else:
        from jax.experimental.pallas import tpu as pltpu

        kernel = functools.partial(_fwd_kernel, scale=scale, causal=causal,
                                   block_q=bq, block_k=bk, nkb=tk // bk)
        o3, lse = pl.pallas_call(
            kernel,
            grid=(bh, tq // bq, tk // bk),
            in_specs=[
                pl.BlockSpec((None, bq, d), lambda i, j, k_: (i, j, 0)),
                pl.BlockSpec((None, bk, d), lambda i, j, k_: (i, k_, 0)),
                pl.BlockSpec((None, bk, d), lambda i, j, k_: (i, k_, 0)),
            ],
            out_specs=[
                pl.BlockSpec((None, bq, d), lambda i, j, k_: (i, j, 0)),
                pl.BlockSpec((None, bq, _LANES),
                             lambda i, j, k_: (i, j, 0)),
            ],
            out_shape=out_shape,
            scratch_shapes=[
                pltpu.VMEM((bq, _LANES), jnp.float32),  # running max m
                pltpu.VMEM((bq, _LANES), jnp.float32),  # running norm l
                pltpu.VMEM((bq, d), jnp.float32),       # unnormalized out
            ],
            interpret=interpret,
        )(q3, k3, v3)
    return _from_bhsd(o3, b, h), (q, k, v, _from_bhsd(o3, b, h), lse)


def _flash_fwd_rule(q, k, v, causal, block_q, block_k, interpret):
    o, res = _flash_fwd(q, k, v, causal, block_q, block_k, interpret)
    return o, res


def _flash_bwd_rule(causal, block_q, block_k, interpret, res, do):
    q, k, v, o, lse = res
    if interpret is None:
        interpret = _interpret_default()
    b, tq, h, d = q.shape
    tk = k.shape[1]
    bq = _pick_block(tq, block_q)
    bk = _pick_block(tk, block_k)
    scale = 1.0 / float(np.sqrt(d))
    bh = b * h

    q3, k3, v3 = _to_bhsd(q), _to_bhsd(k), _to_bhsd(v)
    o3, do3 = _to_bhsd(o), _to_bhsd(do)
    # delta_i = rowsum(dO_i * O_i) — the softmax-jacobian diagonal term,
    # broadcast across the 128-lane stats dim like lse
    delta = jnp.broadcast_to(
        jnp.sum(do3.astype(jnp.float32) * o3.astype(jnp.float32),
                axis=-1, keepdims=True),
        lse.shape)

    # Resident fast paths when the whole-sequence operands fit VMEM (the
    # dkv kernel's 128-lane f32 stats are the tight constraint); beyond
    # that, the reduction axis runs as the innermost grid dimension
    # revisiting an f32 output block — VMEM per step is O(block^2),
    # independent of T.
    dq_resident = tk * d * q.dtype.itemsize <= _RESIDENT_BYTES
    if dq_resident:
        dq_kernel = functools.partial(
            _dq_kernel_resident, scale=scale, causal=causal, block_q=bq,
            block_k=bk, seq_k=tk)
        dq3 = pl.pallas_call(
            dq_kernel,
            grid=(bh, tq // bq),
            in_specs=[
                pl.BlockSpec((None, bq, d), lambda i, j: (i, j, 0)),
                pl.BlockSpec((None, tk, d), lambda i, j: (i, 0, 0)),
                pl.BlockSpec((None, tk, d), lambda i, j: (i, 0, 0)),
                pl.BlockSpec((None, bq, d), lambda i, j: (i, j, 0)),
                pl.BlockSpec((None, bq, _LANES), lambda i, j: (i, j, 0)),
                pl.BlockSpec((None, bq, _LANES), lambda i, j: (i, j, 0)),
            ],
            out_specs=pl.BlockSpec((None, bq, d), lambda i, j: (i, j, 0)),
            out_shape=_sds((bh, tq, d), jnp.float32, q3),
            interpret=interpret,
        )(q3, k3, v3, do3, lse, delta)
    else:
        dq_kernel = functools.partial(_dq_kernel, scale=scale,
                                      causal=causal, block_q=bq, block_k=bk)
        dq3 = pl.pallas_call(
            dq_kernel,
            grid=(bh, tq // bq, tk // bk),
            in_specs=[
                pl.BlockSpec((None, bq, d), lambda i, j, k_: (i, j, 0)),
                pl.BlockSpec((None, bk, d), lambda i, j, k_: (i, k_, 0)),
                pl.BlockSpec((None, bk, d), lambda i, j, k_: (i, k_, 0)),
                pl.BlockSpec((None, bq, d), lambda i, j, k_: (i, j, 0)),
                pl.BlockSpec((None, bq, _LANES),
                             lambda i, j, k_: (i, j, 0)),
                pl.BlockSpec((None, bq, _LANES),
                             lambda i, j, k_: (i, j, 0)),
            ],
            out_specs=pl.BlockSpec((None, bq, d),
                                   lambda i, j, k_: (i, j, 0)),
            out_shape=_sds((bh, tq, d), jnp.float32, q3),
            interpret=interpret,
        )(q3, k3, v3, do3, lse, delta)

    # lse/delta stats are always f32 and get a deliberate 2x allowance
    # (preserves the pre-byte-gate bound: bf16 resident up to T=4096)
    stats_bytes = tq * _LANES * jnp.dtype(jnp.float32).itemsize
    dkv_resident = (tq * d * q.dtype.itemsize <= _RESIDENT_BYTES
                    and stats_bytes <= 2 * _RESIDENT_BYTES)
    if dkv_resident:
        dkv_kernel = functools.partial(
            _dkv_kernel_resident, scale=scale, causal=causal, block_q=bq,
            block_k=bk, seq_q=tq)
        dk3, dv3 = pl.pallas_call(
            dkv_kernel,
            grid=(bh, tk // bk),
            in_specs=[
                pl.BlockSpec((None, tq, d), lambda i, j: (i, 0, 0)),
                pl.BlockSpec((None, bk, d), lambda i, j: (i, j, 0)),
                pl.BlockSpec((None, bk, d), lambda i, j: (i, j, 0)),
                pl.BlockSpec((None, tq, d), lambda i, j: (i, 0, 0)),
                pl.BlockSpec((None, tq, _LANES), lambda i, j: (i, 0, 0)),
                pl.BlockSpec((None, tq, _LANES), lambda i, j: (i, 0, 0)),
            ],
            out_specs=[
                pl.BlockSpec((None, bk, d), lambda i, j: (i, j, 0)),
                pl.BlockSpec((None, bk, d), lambda i, j: (i, j, 0)),
            ],
            out_shape=[
                _sds((bh, tk, d), jnp.float32, q3),
                _sds((bh, tk, d), jnp.float32, q3),
            ],
            interpret=interpret,
        )(q3, k3, v3, do3, lse, delta)
    else:
        dkv_kernel = functools.partial(_dkv_kernel, scale=scale,
                                       causal=causal, block_q=bq,
                                       block_k=bk)
        dk3, dv3 = pl.pallas_call(
            dkv_kernel,
            grid=(bh, tk // bk, tq // bq),
            in_specs=[
                pl.BlockSpec((None, bq, d), lambda i, j, k_: (i, k_, 0)),
                pl.BlockSpec((None, bk, d), lambda i, j, k_: (i, j, 0)),
                pl.BlockSpec((None, bk, d), lambda i, j, k_: (i, j, 0)),
                pl.BlockSpec((None, bq, d), lambda i, j, k_: (i, k_, 0)),
                pl.BlockSpec((None, bq, _LANES),
                             lambda i, j, k_: (i, k_, 0)),
                pl.BlockSpec((None, bq, _LANES),
                             lambda i, j, k_: (i, k_, 0)),
            ],
            out_specs=[
                pl.BlockSpec((None, bk, d), lambda i, j, k_: (i, j, 0)),
                pl.BlockSpec((None, bk, d), lambda i, j, k_: (i, j, 0)),
            ],
            out_shape=[
                _sds((bh, tk, d), jnp.float32, q3),
                _sds((bh, tk, d), jnp.float32, q3),
            ],
            interpret=interpret,
        )(q3, k3, v3, do3, lse, delta)

    return (_from_bhsd(dq3, b, h).astype(q.dtype),
            _from_bhsd(dk3, b, h).astype(k.dtype),
            _from_bhsd(dv3, b, h).astype(v.dtype))


flash_attention.defvjp(_flash_fwd_rule, _flash_bwd_rule)
