"""Flash attention — fused blockwise attention as Pallas TPU kernels.

The hot op of the transformer family (`models/transformer.py`). XLA compiles
the naive `ops.attention` into einsum+softmax+einsum with the full (T, T)
score matrix materialized in HBM; this kernel computes attention blockwise in
VMEM with an online softmax (the FlashAttention-2 formulation), so HBM
traffic is O(T·D) instead of O(T²) and the MXU stays fed from on-chip
memory.

All three kernels share one streaming structure: a 3-D grid
(batch·kv-head, out-block, reduction-block) whose INNERMOST axis is the
reduction, so VMEM holds one (block_q, block_k) tile's operands at a time
— per-step VMEM is O(block²), independent of sequence length:

- forward: grid (bh, q-block, k-block); the online-softmax state
  (running max m, normalizer l, unnormalized acc) persists in VMEM
  scratch across the sequential k steps; the output block is normalized
  and the log-sum-exp saved at the last k step.
- backward-dq: grid (bh, q-block, k-block); recomputes p from (q, k,
  lse), forms ds = p * (dp - delta), and accumulates dq = Σ ds·k into a
  revisited f32 output block.
- backward-dkv: grid (bh, k-block, q-block); accumulates dv = Σ pᵀ·do
  and dk = Σ dsᵀ·q the same way.

Every entry point picks between this streaming form and a resident fast
path (whole K/V — or Q/dO/stats for dkv — held in VMEM with a fori_loop
reduction) when the sequence fits `_RESIDENT_BYTES`; resident is ~10%
faster at T=8k (no per-tile scratch round-trips) and its causal/window
loop bounds skip masked tiles' DMA entirely. In the streaming form,
masked-out tiles skip their COMPUTE with `pl.when` (whole-tile Mosaic
predication) but the grid still visits them, so their block DMA traffic
is not saved — the FLOP savings of the old loop bounds are kept, the
bandwidth savings only on the resident path.

**Sliding windows** (`window > 0`): position i sees keys
[i - window + 1, i] — identical semantics to `ops.attention`'s
`window=` mask. Out-of-window k-tiles are skipped exactly like causal
future tiles: shrunk fori_loop bounds on the resident paths (their DMA
never issues), `pl.when` tile-liveness on the streaming paths. A long
sequence with a small window therefore costs O(T·window), not O(T²).

**Grouped-query attention** is native: pass k/v with fewer heads
(n_kv_heads) than q and the kernels never materialize repeated K/V.
Group folding maps GQA onto the exact same kernel bodies: q's heads
fold as extra ROWS — (B, T, H, D) -> (B·Hkv, G·T, D) with each
G-chunk of rows one query head sharing that kv head — so every q-row
block attends against the SAME resident/streamed K/V tile, which is
precisely the reuse GQA exists to exploit. Kernels recover logical
positions as `row mod T` (blocks never straddle chunks since
block_q | T). MHA is the G=1 special case — one code path.

Wrapped in `jax.custom_vjp`, so `jax.grad` through the transformer uses the
fused backward. On non-TPU backends the kernels run in Pallas interpret mode
(exact same code path, used by the CPU test suite); on TPU they compile via
Mosaic. Layout contract matches `ops.attention`: (batch, seq, heads,
head_dim).

Written per /opt/skills/guides/pallas_guide.md (blockwise VMEM tiling,
online-softmax accumulators, preferred_element_type=f32 on every MXU dot,
@pl.when for edge blocks).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl

_NEG = -1e30  # plain float: jnp scalars would be captured consts in kernels
_LANES = 128  # Mosaic min lane width: row stats (lse/delta) pad to this


def _interpret_default() -> bool:
    return jax.default_backend() != "tpu"


# Resident-K/V fast path bound: with tk*d at or under this, the whole K and
# V comfortably fit VMEM next to the working blocks, and the single-kernel
# fori_loop formulation avoids the streaming version's per-tile scratch
# round-trips (~10% at T=8k measured). Above it, stream (VMEM-unbounded).
# Byte-based (dtype-aware): 8k x 64 f32 K/V picks streaming while the same
# shape in bf16 stays resident — an element-count gate let the f32 case
# overflow the 16MB scoped-vmem ceiling by a hair.
_RESIDENT_BYTES = 1 << 20  # 1MB per whole-sequence operand held in VMEM


def _mask(s, qrow, kcol, causal, window):
    """Apply the causal and/or sliding-window mask to a score tile.
    Returns (masked scores, validity mask or None)."""
    valid = None
    if causal:
        valid = qrow >= kcol
    if window > 0:
        wv = kcol > qrow - window
        valid = wv if valid is None else valid & wv
    if valid is not None:
        s = jnp.where(valid, s, _NEG)
    return s, valid


def _kblock_bounds(iqm, block_q, block_k, nkb, causal, window):
    """fori_loop bounds over k-blocks for the q block with chunk-local
    index `iqm` (resident fwd/dq paths). Tiles outside [lo, hi) contain
    no unmasked entry — their DMA is never issued."""
    lo = 0
    hi = nkb
    if causal:
        hi = jnp.minimum(nkb, (iqm * block_q + block_q - 1) // block_k + 1)
    if window > 0:
        first_col = jnp.maximum(0, iqm * block_q - (window - 1))
        lo = first_col // block_k
    return lo, hi


def _tile_live(iqm, jk, block_q, block_k, causal, window):
    """Whether the (iqm, jk) tile has any unmasked entry (streaming
    paths' `pl.when` predicate). `iqm` is the chunk-local q-block index."""
    live = True
    if causal:  # last q row >= first k col
        live = (iqm * block_q + block_q - 1) >= (jk * block_k)
    if window > 0:  # last k col inside the earliest row's window
        wlive = (jk * block_k + block_k - 1) >= (iqm * block_q - (window - 1))
        live = wlive if live is True else live & wlive
    return live


# ----------------------------------------------------------------- forward


def _fwd_kernel_resident(q_ref, k_ref, v_ref, o_ref, lse_ref, *, scale,
                         causal, window, block_q, block_k, seq_k,
                         nqb_chunk):
    """Grid (bh, nqb): whole K/V resident in VMEM, fori_loop over k-blocks
    with the online-softmax carry in registers. Fast path for small T."""
    iq = pl.program_id(1)
    iqm = iq % nqb_chunk  # chunk-local block index (GQA row folding)
    q = q_ref[:].astype(jnp.float32)                       # (bq, D)
    d = q.shape[-1]

    nkb = seq_k // block_k
    lo, hi = _kblock_bounds(iqm, block_q, block_k, nkb, causal, window)

    qrow = iqm * block_q + jax.lax.broadcasted_iota(
        jnp.int32, (block_q, block_k), 0)

    def body(j, carry):
        m, l, acc = carry
        kb = k_ref[pl.ds(j * block_k, block_k), :].astype(jnp.float32)
        vb = v_ref[pl.ds(j * block_k, block_k), :].astype(jnp.float32)
        s = jnp.dot(q, kb.T, preferred_element_type=jnp.float32) * scale
        kcol = j * block_k + jax.lax.broadcasted_iota(
            jnp.int32, (block_q, block_k), 1)
        s, valid = _mask(s, qrow, kcol, causal, window)
        m_new = jnp.maximum(m, s.max(axis=-1, keepdims=True))
        p = jnp.exp(s - m_new)
        if valid is not None:
            p = jnp.where(valid, p, 0.0)
        alpha = jnp.exp(m - m_new)
        l_new = l * alpha + p.sum(axis=-1, keepdims=True)
        acc_new = acc * alpha + jnp.dot(
            p, vb, preferred_element_type=jnp.float32)
        return m_new, l_new, acc_new

    m0 = jnp.full((block_q, 1), _NEG)
    l0 = jnp.zeros((block_q, 1), jnp.float32)
    acc0 = jnp.zeros((block_q, d), jnp.float32)
    m, l, acc = jax.lax.fori_loop(lo, hi, body, (m0, l0, acc0))

    o_ref[:] = (acc / jnp.maximum(l, 1e-30)).astype(o_ref.dtype)
    lse_ref[:] = jnp.broadcast_to(
        m + jnp.log(jnp.maximum(l, 1e-30)), (block_q, _LANES))


def _fwd_kernel(q_ref, k_ref, v_ref, o_ref, lse_ref, m_scr, l_scr, acc_scr,
                *, scale, causal, window, block_q, block_k, nkb,
                nqb_chunk):
    """Grid (bh, nqb, nkb) — the K reduction is the INNERMOST grid axis,
    so VMEM holds one (block_q, block_k)-tile's operands at a time; the
    online-softmax state (m, l, acc) lives in scratch that persists
    across the sequential innermost steps, and the (bh, iq) output block
    is finalized at the last K step. Fully-masked causal/window tiles
    skip their matmuls via `pl.when`."""
    iq = pl.program_id(1)
    jk = pl.program_id(2)
    iqm = iq % nqb_chunk

    @pl.when(jk == 0)
    def _init():
        m_scr[:] = jnp.full_like(m_scr, _NEG)
        l_scr[:] = jnp.zeros_like(l_scr)
        acc_scr[:] = jnp.zeros_like(acc_scr)

    live = _tile_live(iqm, jk, block_q, block_k, causal, window)

    @pl.when(live)
    def _accum():
        q = q_ref[:].astype(jnp.float32)                   # (bq, D)
        kb = k_ref[:].astype(jnp.float32)                  # (bk, D)
        vb = v_ref[:].astype(jnp.float32)
        s = jnp.dot(q, kb.T, preferred_element_type=jnp.float32) * scale
        qrow = iqm * block_q + jax.lax.broadcasted_iota(
            jnp.int32, (block_q, block_k), 0)
        kcol = jk * block_k + jax.lax.broadcasted_iota(
            jnp.int32, (block_q, block_k), 1)
        s, valid = _mask(s, qrow, kcol, causal, window)
        m = m_scr[:, 0:1]
        l = l_scr[:, 0:1]
        m_new = jnp.maximum(m, s.max(axis=-1, keepdims=True))
        p = jnp.exp(s - m_new)
        if valid is not None:
            p = jnp.where(valid, p, 0.0)
        alpha = jnp.exp(m - m_new)
        l_new = l * alpha + p.sum(axis=-1, keepdims=True)
        acc_scr[:] = acc_scr[:] * alpha + jnp.dot(
            p, vb, preferred_element_type=jnp.float32)
        m_scr[:] = jnp.broadcast_to(m_new, m_scr.shape)
        l_scr[:] = jnp.broadcast_to(l_new, l_scr.shape)

    @pl.when(jk == nkb - 1)
    def _finalize():
        l = l_scr[:, 0:1]
        o_ref[:] = (acc_scr[:] / jnp.maximum(l, 1e-30)).astype(o_ref.dtype)
        # row stats broadcast across a 128-lane dim (Mosaic min tile width)
        lse_ref[:] = jnp.broadcast_to(
            m_scr[:, 0:1] + jnp.log(jnp.maximum(l, 1e-30)),
            (block_q, _LANES))


# ---------------------------------------------------------------- backward


def _dq_kernel_resident(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
                        dq_ref, *, scale, causal, window, block_q, block_k,
                        seq_k, nqb_chunk):
    """Grid (bh, nqb): whole K/V resident in VMEM, fori_loop over k-blocks
    with shrunk causal/window bounds. Fast path for small T."""
    iq = pl.program_id(1)
    iqm = iq % nqb_chunk
    q = q_ref[:].astype(jnp.float32)
    do = do_ref[:].astype(jnp.float32)
    lse = lse_ref[:, 0:1]
    delta = delta_ref[:, 0:1]
    d = q.shape[-1]

    nkb = seq_k // block_k
    lo, hi = _kblock_bounds(iqm, block_q, block_k, nkb, causal, window)

    qrow = iqm * block_q + jax.lax.broadcasted_iota(
        jnp.int32, (block_q, block_k), 0)

    def body(j, dq):
        kb = k_ref[pl.ds(j * block_k, block_k), :].astype(jnp.float32)
        vb = v_ref[pl.ds(j * block_k, block_k), :].astype(jnp.float32)
        s = jnp.dot(q, kb.T, preferred_element_type=jnp.float32) * scale
        kcol = j * block_k + jax.lax.broadcasted_iota(
            jnp.int32, (block_q, block_k), 1)
        s, _valid = _mask(s, qrow, kcol, causal, window)
        p = jnp.exp(s - lse)
        dp = jnp.dot(do, vb.T, preferred_element_type=jnp.float32)
        ds = p * (dp - delta) * scale
        return dq + jnp.dot(ds, kb, preferred_element_type=jnp.float32)

    dq = jax.lax.fori_loop(
        lo, hi, body, jnp.zeros((block_q, d), jnp.float32))
    dq_ref[:] = dq.astype(dq_ref.dtype)


def _dkv_kernel_resident(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
                         dk_ref, dv_ref, *, scale, causal, window, block_q,
                         block_k, seq_q, nqb_chunk, groups):
    """Grid (bh, nkb): whole Q/dO/stats resident in VMEM; for each of the
    `groups` query-head chunks (GQA row folding; static unroll), a
    fori_loop from that chunk's first live q-block accumulates into the
    SHARED dk/dv block. Fast path for small T — the stats are
    (T, 128)-lane f32, so this path's VMEM grows 512B/row and is gated
    tighter than the forward's."""
    jk = pl.program_id(1)
    kb = k_ref[:].astype(jnp.float32)                      # (bk, D)
    vb = v_ref[:].astype(jnp.float32)
    d = kb.shape[-1]

    # chunk-local q-block bounds: blocks before `first` (causal) or past
    # `last` (window) contain no unmasked entry for this k block
    first = (jk * block_k) // block_q if causal else 0
    if window > 0:
        last = jnp.minimum(
            nqb_chunk,
            (jk * block_k + block_k - 1 + window - 1) // block_q + 1)
    else:
        last = nqb_chunk

    kcol = jk * block_k + jax.lax.broadcasted_iota(
        jnp.int32, (block_q, block_k), 1)

    def chunk_body(base, carry):
        # `base` = this chunk's first block index in the folded row space
        def body(i, carry):
            dk, dv = carry
            row0 = (base + i) * block_q
            qb = q_ref[pl.ds(row0, block_q), :].astype(jnp.float32)
            dob = do_ref[pl.ds(row0, block_q), :].astype(jnp.float32)
            lse = lse_ref[pl.ds(row0, block_q), 0:1]
            delta = delta_ref[pl.ds(row0, block_q), 0:1]
            s = jnp.dot(qb, kb.T,
                        preferred_element_type=jnp.float32) * scale
            qrow = i * block_q + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 0)
            s, _valid = _mask(s, qrow, kcol, causal, window)
            p = jnp.exp(s - lse)
            dv = dv + jnp.dot(p.T, dob,
                              preferred_element_type=jnp.float32)
            dp = jnp.dot(dob, vb.T, preferred_element_type=jnp.float32)
            ds = p * (dp - delta) * scale
            dk = dk + jnp.dot(ds.T, qb,
                              preferred_element_type=jnp.float32)
            return dk, dv

        return jax.lax.fori_loop(first, last, body, carry)

    dk = jnp.zeros((block_k, d), jnp.float32)
    dv = jnp.zeros((block_k, d), jnp.float32)
    for gi in range(groups):  # static: groups is a compile-time constant
        dk, dv = chunk_body(gi * nqb_chunk, (dk, dv))
    dk_ref[:] = dk.astype(dk_ref.dtype)
    dv_ref[:] = dv.astype(dv_ref.dtype)


def _dq_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref, dq_ref, *,
               scale, causal, window, block_q, block_k, nqb_chunk):
    """Grid (bh, nqb, nkb) — the K reduction runs as the INNERMOST grid
    axis so VMEM holds one (block_q, block_k)-tile's operands at a time
    (the previous whole-sequence block specs hit the scoped-vmem ceiling
    at T≈8k); dq_ref is the (bh, iq) block, revisited across j, f32
    accumulated. Fully-masked causal/window tiles skip their matmuls via
    `pl.when` (Mosaic predication), preserving the old loop-bound
    optimization."""
    iq = pl.program_id(1)
    jk = pl.program_id(2)
    iqm = iq % nqb_chunk

    @pl.when(jk == 0)
    def _init():
        dq_ref[:] = jnp.zeros_like(dq_ref)

    live = _tile_live(iqm, jk, block_q, block_k, causal, window)

    @pl.when(live)
    def _accum():
        q = q_ref[:].astype(jnp.float32)
        do = do_ref[:].astype(jnp.float32)
        lse = lse_ref[:, 0:1]
        delta = delta_ref[:, 0:1]
        kb = k_ref[:].astype(jnp.float32)
        vb = v_ref[:].astype(jnp.float32)
        s = jnp.dot(q, kb.T, preferred_element_type=jnp.float32) * scale
        qrow = iqm * block_q + jax.lax.broadcasted_iota(
            jnp.int32, (block_q, block_k), 0)
        kcol = jk * block_k + jax.lax.broadcasted_iota(
            jnp.int32, (block_q, block_k), 1)
        s, _valid = _mask(s, qrow, kcol, causal, window)
        p = jnp.exp(s - lse)
        dp = jnp.dot(do, vb.T, preferred_element_type=jnp.float32)
        ds = p * (dp - delta) * scale
        dq_ref[:] += jnp.dot(ds, kb, preferred_element_type=jnp.float32)


def _dkv_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
                dk_ref, dv_ref, *, scale, causal, window, block_q,
                block_k, nqb_chunk):
    """Grid (bh, nkb, nqb_total) — Q reduction innermost (across ALL
    query-group chunks under GQA, so group members' contributions
    accumulate into the shared dk/dv block), (bh, jk) output block
    revisited across i with f32 accumulation; same VMEM story as
    `_dq_kernel`."""
    jk = pl.program_id(1)
    iq = pl.program_id(2)
    iqm = iq % nqb_chunk

    @pl.when(iq == 0)
    def _init():
        dk_ref[:] = jnp.zeros_like(dk_ref)
        dv_ref[:] = jnp.zeros_like(dv_ref)

    live = _tile_live(iqm, jk, block_q, block_k, causal, window)

    @pl.when(live)
    def _accum():
        kb = k_ref[:].astype(jnp.float32)                  # (bk, D)
        vb = v_ref[:].astype(jnp.float32)
        qb = q_ref[:].astype(jnp.float32)
        dob = do_ref[:].astype(jnp.float32)
        lse = lse_ref[:, 0:1]
        delta = delta_ref[:, 0:1]
        s = jnp.dot(qb, kb.T, preferred_element_type=jnp.float32) * scale
        qrow = iqm * block_q + jax.lax.broadcasted_iota(
            jnp.int32, (block_q, block_k), 0)
        kcol = jk * block_k + jax.lax.broadcasted_iota(
            jnp.int32, (block_q, block_k), 1)
        s, _valid = _mask(s, qrow, kcol, causal, window)
        p = jnp.exp(s - lse)
        dv_ref[:] += jnp.dot(p.T, dob, preferred_element_type=jnp.float32)
        dp = jnp.dot(dob, vb.T, preferred_element_type=jnp.float32)
        ds = p * (dp - delta) * scale
        dk_ref[:] += jnp.dot(ds.T, qb, preferred_element_type=jnp.float32)


# ------------------------------------------------------------- entry points


def _to_bhsd(x):
    """(B, T, H, D) -> (B*H, T, D) for the (batch·head, block) grid."""
    b, t, h, d = x.shape
    return jnp.reshape(jnp.transpose(x, (0, 2, 1, 3)), (b * h, t, d))


def _from_bhsd(x, b, h):
    bh, t, d = x.shape
    return jnp.transpose(jnp.reshape(x, (b, h, t, d)), (0, 2, 1, 3))


def _fold_q(x, kvh):
    """GQA row folding: (B, T, H, D) -> (B*Hkv, G*T, D) where query head
    h = kv*G + g lands in rows [g*T, (g+1)*T) of batch-row b*Hkv + kv —
    each G-chunk of rows is one query head sharing that kv head."""
    b, t, h, d = x.shape
    g = h // kvh
    x = jnp.transpose(x, (0, 2, 1, 3))          # (B, H, T, D)
    return jnp.reshape(x, (b * kvh, g * t, d))  # heads split as (kvh, g)


def _unfold_q(x, b, h):
    """Inverse of `_fold_q`: (B*Hkv, G*T, D) -> (B, T, H, D)."""
    bkv, gt, d = x.shape
    kvh = bkv // b
    g = h // kvh
    x = jnp.reshape(x, (b, kvh, g, gt // g, d))
    x = jnp.reshape(x, (b, h, gt // g, d))
    return jnp.transpose(x, (0, 2, 1, 3))


def _pick_block(t: int, want: int) -> int:
    while t % want:
        want //= 2
    return max(want, 1)


def _sds(shape, dtype, like):
    """ShapeDtypeStruct inheriting `like`'s shard_map variance (vma), so the
    kernels compose with explicit-sharding engines (pallas_call under
    shard_map requires explicit output vma)."""
    vma = getattr(getattr(like, "aval", None), "vma", None)
    if vma:
        return jax.ShapeDtypeStruct(shape, dtype, vma=vma)
    return jax.ShapeDtypeStruct(shape, dtype)


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6, 7))
def flash_attention(q, k, v, causal: bool = True, window: int = 0,
                    block_q: int = 512, block_k: int = 512,
                    interpret: bool | None = None):
    """Fused multi-head attention; same contract as `ops.attention`.

    q: (batch, seq, heads, head_dim); k, v: (batch, seq, kv_heads,
    head_dim) with kv_heads | heads — kv_heads < heads is native GQA (no
    repeated K/V is ever materialized). Returns (batch, seq, heads,
    head_dim). `window > 0` restricts position i to keys
    [i - window + 1, i] (sliding-window attention; out-of-window tiles
    are skipped, not just masked). Sequence lengths must be divisible by
    the (auto-shrunk) block sizes.
    `interpret=None` auto-selects Pallas interpret mode off-TPU.
    """
    o, _ = _flash_fwd(q, k, v, causal, window, block_q, block_k, interpret)
    return o


flash_attention.supports_gqa = True
flash_attention.supports_window = True


def _flash_fwd(q, k, v, causal, window, block_q, block_k, interpret):
    if interpret is None:
        interpret = _interpret_default()
    b, tq, h, d = q.shape
    tk = k.shape[1]
    kvh = k.shape[2]
    assert h % kvh == 0, (h, kvh)
    g = h // kvh
    bq = _pick_block(tq, block_q)
    bk = _pick_block(tk, block_k)
    nqb_chunk = tq // bq
    scale = 1.0 / float(np.sqrt(d))
    window = int(window)

    q3 = _fold_q(q, kvh)                         # (b*kvh, g*tq, d)
    k3, v3 = _to_bhsd(k), _to_bhsd(v)            # (b*kvh, tk, d)
    bh = b * kvh
    rows = g * tq

    out_shape = [
        _sds((bh, rows, d), q.dtype, q3),
        _sds((bh, rows, _LANES), jnp.float32, q3),
    ]
    if tk * d * q.dtype.itemsize <= _RESIDENT_BYTES:
        kernel = functools.partial(
            _fwd_kernel_resident, scale=scale, causal=causal,
            window=window, block_q=bq, block_k=bk, seq_k=tk,
            nqb_chunk=nqb_chunk)
        o3, lse = pl.pallas_call(
            kernel,
            grid=(bh, rows // bq),
            in_specs=[
                pl.BlockSpec((None, bq, d), lambda i, j: (i, j, 0)),
                pl.BlockSpec((None, tk, d), lambda i, j: (i, 0, 0)),
                pl.BlockSpec((None, tk, d), lambda i, j: (i, 0, 0)),
            ],
            out_specs=[
                pl.BlockSpec((None, bq, d), lambda i, j: (i, j, 0)),
                pl.BlockSpec((None, bq, _LANES), lambda i, j: (i, j, 0)),
            ],
            out_shape=out_shape,
            interpret=interpret,
        )(q3, k3, v3)
    else:
        from jax.experimental.pallas import tpu as pltpu

        kernel = functools.partial(
            _fwd_kernel, scale=scale, causal=causal, window=window,
            block_q=bq, block_k=bk, nkb=tk // bk, nqb_chunk=nqb_chunk)
        o3, lse = pl.pallas_call(
            kernel,
            grid=(bh, rows // bq, tk // bk),
            in_specs=[
                pl.BlockSpec((None, bq, d), lambda i, j, k_: (i, j, 0)),
                pl.BlockSpec((None, bk, d), lambda i, j, k_: (i, k_, 0)),
                pl.BlockSpec((None, bk, d), lambda i, j, k_: (i, k_, 0)),
            ],
            out_specs=[
                pl.BlockSpec((None, bq, d), lambda i, j, k_: (i, j, 0)),
                pl.BlockSpec((None, bq, _LANES),
                             lambda i, j, k_: (i, j, 0)),
            ],
            out_shape=out_shape,
            scratch_shapes=[
                pltpu.VMEM((bq, _LANES), jnp.float32),  # running max m
                pltpu.VMEM((bq, _LANES), jnp.float32),  # running norm l
                pltpu.VMEM((bq, d), jnp.float32),       # unnormalized out
            ],
            interpret=interpret,
        )(q3, k3, v3)
    o = _unfold_q(o3, b, h)
    return o, (q, k, v, o, lse)


def _flash_fwd_rule(q, k, v, causal, window, block_q, block_k, interpret):
    o, res = _flash_fwd(q, k, v, causal, window, block_q, block_k,
                        interpret)
    return o, res


def _flash_bwd_rule(causal, window, block_q, block_k, interpret, res, do):
    q, k, v, o, lse = res
    if interpret is None:
        interpret = _interpret_default()
    b, tq, h, d = q.shape
    tk = k.shape[1]
    kvh = k.shape[2]
    g = h // kvh
    bq = _pick_block(tq, block_q)
    bk = _pick_block(tk, block_k)
    nqb_chunk = tq // bq
    scale = 1.0 / float(np.sqrt(d))
    window = int(window)
    bh = b * kvh
    rows = g * tq

    q3, k3, v3 = _fold_q(q, kvh), _to_bhsd(k), _to_bhsd(v)
    o3, do3 = _fold_q(o, kvh), _fold_q(do, kvh)
    # delta_i = rowsum(dO_i * O_i) — the softmax-jacobian diagonal term,
    # broadcast across the 128-lane stats dim like lse
    delta = jnp.broadcast_to(
        jnp.sum(do3.astype(jnp.float32) * o3.astype(jnp.float32),
                axis=-1, keepdims=True),
        lse.shape)

    # Resident fast paths when the whole-sequence operands fit VMEM (the
    # dkv kernel's 128-lane f32 stats are the tight constraint); beyond
    # that, the reduction axis runs as the innermost grid dimension
    # revisiting an f32 output block — VMEM per step is O(block^2),
    # independent of T.
    dq_resident = tk * d * q.dtype.itemsize <= _RESIDENT_BYTES
    if dq_resident:
        dq_kernel = functools.partial(
            _dq_kernel_resident, scale=scale, causal=causal, window=window,
            block_q=bq, block_k=bk, seq_k=tk, nqb_chunk=nqb_chunk)
        dq3 = pl.pallas_call(
            dq_kernel,
            grid=(bh, rows // bq),
            in_specs=[
                pl.BlockSpec((None, bq, d), lambda i, j: (i, j, 0)),
                pl.BlockSpec((None, tk, d), lambda i, j: (i, 0, 0)),
                pl.BlockSpec((None, tk, d), lambda i, j: (i, 0, 0)),
                pl.BlockSpec((None, bq, d), lambda i, j: (i, j, 0)),
                pl.BlockSpec((None, bq, _LANES), lambda i, j: (i, j, 0)),
                pl.BlockSpec((None, bq, _LANES), lambda i, j: (i, j, 0)),
            ],
            out_specs=pl.BlockSpec((None, bq, d), lambda i, j: (i, j, 0)),
            out_shape=_sds((bh, rows, d), jnp.float32, q3),
            interpret=interpret,
        )(q3, k3, v3, do3, lse, delta)
    else:
        dq_kernel = functools.partial(
            _dq_kernel, scale=scale, causal=causal, window=window,
            block_q=bq, block_k=bk, nqb_chunk=nqb_chunk)
        dq3 = pl.pallas_call(
            dq_kernel,
            grid=(bh, rows // bq, tk // bk),
            in_specs=[
                pl.BlockSpec((None, bq, d), lambda i, j, k_: (i, j, 0)),
                pl.BlockSpec((None, bk, d), lambda i, j, k_: (i, k_, 0)),
                pl.BlockSpec((None, bk, d), lambda i, j, k_: (i, k_, 0)),
                pl.BlockSpec((None, bq, d), lambda i, j, k_: (i, j, 0)),
                pl.BlockSpec((None, bq, _LANES),
                             lambda i, j, k_: (i, j, 0)),
                pl.BlockSpec((None, bq, _LANES),
                             lambda i, j, k_: (i, j, 0)),
            ],
            out_specs=pl.BlockSpec((None, bq, d),
                                   lambda i, j, k_: (i, j, 0)),
            out_shape=_sds((bh, rows, d), jnp.float32, q3),
            interpret=interpret,
        )(q3, k3, v3, do3, lse, delta)

    # lse/delta stats are always f32 and get a deliberate 2x allowance
    # (preserves the pre-byte-gate bound: bf16 resident up to T=4096).
    # Under GQA the folded row space is g*tq long and the WHOLE folded
    # Q/dO/stats must sit in VMEM, so both gates are absolute in `rows`.
    stats_bytes = rows * _LANES * jnp.dtype(jnp.float32).itemsize
    dkv_resident = (rows * d * q.dtype.itemsize <= _RESIDENT_BYTES
                    and stats_bytes <= 2 * _RESIDENT_BYTES)
    if dkv_resident:
        dkv_kernel = functools.partial(
            _dkv_kernel_resident, scale=scale, causal=causal,
            window=window, block_q=bq, block_k=bk, seq_q=tq,
            nqb_chunk=nqb_chunk, groups=g)
        dk3, dv3 = pl.pallas_call(
            dkv_kernel,
            grid=(bh, tk // bk),
            in_specs=[
                pl.BlockSpec((None, rows, d), lambda i, j: (i, 0, 0)),
                pl.BlockSpec((None, bk, d), lambda i, j: (i, j, 0)),
                pl.BlockSpec((None, bk, d), lambda i, j: (i, j, 0)),
                pl.BlockSpec((None, rows, d), lambda i, j: (i, 0, 0)),
                pl.BlockSpec((None, rows, _LANES), lambda i, j: (i, 0, 0)),
                pl.BlockSpec((None, rows, _LANES), lambda i, j: (i, 0, 0)),
            ],
            out_specs=[
                pl.BlockSpec((None, bk, d), lambda i, j: (i, j, 0)),
                pl.BlockSpec((None, bk, d), lambda i, j: (i, j, 0)),
            ],
            out_shape=[
                _sds((bh, tk, d), jnp.float32, q3),
                _sds((bh, tk, d), jnp.float32, q3),
            ],
            interpret=interpret,
        )(q3, k3, v3, do3, lse, delta)
    else:
        dkv_kernel = functools.partial(
            _dkv_kernel, scale=scale, causal=causal, window=window,
            block_q=bq, block_k=bk, nqb_chunk=nqb_chunk)
        dk3, dv3 = pl.pallas_call(
            dkv_kernel,
            grid=(bh, tk // bk, rows // bq),
            in_specs=[
                pl.BlockSpec((None, bq, d), lambda i, j, k_: (i, k_, 0)),
                pl.BlockSpec((None, bk, d), lambda i, j, k_: (i, j, 0)),
                pl.BlockSpec((None, bk, d), lambda i, j, k_: (i, j, 0)),
                pl.BlockSpec((None, bq, d), lambda i, j, k_: (i, k_, 0)),
                pl.BlockSpec((None, bq, _LANES),
                             lambda i, j, k_: (i, k_, 0)),
                pl.BlockSpec((None, bq, _LANES),
                             lambda i, j, k_: (i, k_, 0)),
            ],
            out_specs=[
                pl.BlockSpec((None, bk, d), lambda i, j, k_: (i, j, 0)),
                pl.BlockSpec((None, bk, d), lambda i, j, k_: (i, j, 0)),
            ],
            out_shape=[
                _sds((bh, tk, d), jnp.float32, q3),
                _sds((bh, tk, d), jnp.float32, q3),
            ],
            interpret=interpret,
        )(q3, k3, v3, do3, lse, delta)

    return (_unfold_q(dq3, b, h).astype(q.dtype),
            _from_bhsd(dk3, b, kvh).astype(k.dtype),
            _from_bhsd(dv3, b, kvh).astype(v.dtype))


flash_attention.defvjp(_flash_fwd_rule, _flash_bwd_rule)
