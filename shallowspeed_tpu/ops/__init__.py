from shallowspeed_tpu.ops.functional import (  # noqa: F401
    linear,
    linear_grad,
    mse_loss,
    mse_loss_grad,
    relu,
    relu_grad,
    softmax,
    softmax_grad,
)
from shallowspeed_tpu.ops.attention import (  # noqa: F401
    attention,
    ring_attention,
    ulysses_attention,
)
from shallowspeed_tpu.ops.flash_attention import (  # noqa: F401
    flash_attention,
    ring_flash_attention,
)
from shallowspeed_tpu.ops.moe import (  # noqa: F401
    expert_capacity,
    moe_ffn,
    router_z_loss,
    topk_capacity_routing,
)
