from shallowspeed_tpu.ops.functional import (  # noqa: F401
    linear,
    linear_grad,
    mse_loss,
    mse_loss_grad,
    relu,
    relu_grad,
    softmax,
    softmax_grad,
)
from shallowspeed_tpu.ops.attention import (  # noqa: F401
    attention,
    ring_attention,
    ulysses_attention,
)
# NOTE: the `flash_attention` FUNCTION is deliberately not re-exported
# here — binding that name on the package would shadow the
# `ops.flash_attention` SUBMODULE attribute and break
# `import shallowspeed_tpu.ops.flash_attention as fa` (the function name
# equals its module name). Import it from the submodule.
from shallowspeed_tpu.ops.flash_attention import (  # noqa: F401
    ring_flash_attention,
)
from shallowspeed_tpu.ops.moe import (  # noqa: F401
    expert_capacity,
    moe_ffn,
    router_z_loss,
    topk_capacity_routing,
)
