"""Attention ops — full softmax attention and ring attention.

The reference has no attention anywhere (SURVEY §2: the model zoo is an
attention-free MLP, `/root/reference/shallowspeed/layers.py:236-270`), so this
module is a capability *extension*: long-context sequence/context parallelism
is first-class in this framework, built the TPU way:

- `attention`: plain batched multi-head attention, one fused XLA program —
  two MXU einsums around a VPU softmax. The single-device reference
  semantics for the ring variant.
- `ring_attention`: blockwise attention over a sequence-sharded mesh axis.
  Each device owns one sequence block of Q/K/V; K/V blocks rotate around the
  ring with `lax.ppermute` (one ICI neighbor hop per step) while each device
  accumulates its queries' attention with an online-softmax running
  (max, sum, out) state — numerically identical (up to fp reorder) to full
  attention over the gathered sequence, with O(T_local) memory and
  compute/communication overlap (the ppermute of step i+1's block overlaps
  the einsums of step i under XLA's latency-hiding scheduler).
- `ulysses_attention`: DeepSpeed-Ulysses-style all-to-all sequence
  parallelism over the same sequence-sharded axis. Two `lax.all_to_all`s
  re-shard (seq-sharded, all heads) -> (all seq, head-sharded) so each
  device runs plain full attention for its head subset, then the reverse
  all-to-all restores sequence sharding. Requires heads % axis_size == 0;
  comm volume is O(T·d/n) per device per all-to-all (vs the ring's n
  ppermute hops of K/V) and the attention itself is the single fused XLA
  program — the better choice when heads >= devices and T is moderate.

Both are differentiable with `jax.grad` (the transformer family uses JAX
autodiff as its autograd, unlike the MLP family's hand-written VJPs that
mirror the reference's manual backprop layer).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import Array, lax

_NEG = jnp.float32(-1e30)


def attention(q: Array, k: Array, v: Array, causal: bool = True,
              window: int = 0) -> Array:
    """Multi-head scaled-dot-product attention.

    q, k, v: (batch, seq, heads, head_dim). Returns (batch, seq, heads,
    head_dim). With `causal`, position i attends to positions <= i;
    `window > 0` additionally restricts attention to the last `window`
    positions (sliding-window / local attention, Mistral-style: position
    i sees [i - window + 1, i]).

    Mixed-precision safe: scores accumulate in float32 on the MXU
    (`preferred_element_type`) and the softmax runs in float32 regardless
    of the input dtype; only the probability @ V matmul runs in the input
    dtype. With float32 inputs every cast is a no-op.
    """
    scale = 1.0 / jnp.sqrt(jnp.float32(q.shape[-1]))
    s = jnp.einsum("bqhd,bkhd->bhqk", q, k,
                   preferred_element_type=jnp.float32) * scale
    if causal or window > 0:
        tq, tk = q.shape[1], k.shape[1]
        iq, ik = jnp.arange(tq)[:, None], jnp.arange(tk)[None, :]
        mask = iq >= ik if causal else jnp.ones((tq, tk), bool)
        if window > 0:
            mask = mask & (ik > iq - window)
        s = jnp.where(mask, s, _NEG)
    p = jax.nn.softmax(s, axis=-1)
    out = jnp.einsum("bhqk,bkhd->bqhd", p.astype(v.dtype), v,
                     preferred_element_type=jnp.float32)
    return out.astype(q.dtype)


def ulysses_attention(q: Array, k: Array, v: Array, axis_name: str,
                      causal: bool = True, use_flash: bool = False) -> Array:
    """All-to-all (Ulysses) attention over the sequence-sharded `axis_name`.

    q, k, v: (batch, seq_local, heads, head_dim) — this device's sequence
    block, same contract as `ring_attention`. Returns this device's
    (batch, seq_local, heads, head_dim) output, equal (up to float
    reassociation) to slicing full `attention` over the gathered sequence.

    The first all-to-all turns the sequence sharding into a *head* sharding
    (each device receives every sequence block for heads
    [idx*h/n, (idx+1)*h/n)); `tiled=True` concatenates received blocks in
    mesh-axis order, so the gathered sequence axis is already in global
    order and the plain causal mask is correct. After local full attention,
    the reverse all-to-all restores sequence sharding.

    `use_flash` swaps the local attention for the fused Pallas flash
    kernel (`ops/flash_attention.py`): because each device holds the FULL
    gathered sequence for its head subset, the kernel's standard causal
    mask applies unchanged — sequence parallelism and the flash kernel
    compose with no kernel modifications (unlike the ring formulation,
    which would need cross-block position-offset masking inside the
    kernel).
    """
    n = lax.psum(1, axis_name)
    h = q.shape[2]
    assert h % n == 0, (
        f"ulysses_attention needs heads ({h}) divisible by the "
        f"'{axis_name}' axis size ({n}); use ring_attention otherwise")

    def gather_seq(x):  # (b, t/n, h, d) -> (b, t, h/n, d)
        return lax.all_to_all(x, axis_name, split_axis=2, concat_axis=1,
                              tiled=True)

    if use_flash:
        from shallowspeed_tpu.ops.flash_attention import flash_attention

        o = flash_attention(gather_seq(q), gather_seq(k), gather_seq(v),
                            causal=causal)
    else:
        o = attention(gather_seq(q), gather_seq(k), gather_seq(v),
                      causal=causal)
    # (b, t, h/n, d) -> (b, t/n, h, d)
    return lax.all_to_all(o, axis_name, split_axis=1, concat_axis=2,
                          tiled=True)


def ring_attention(q: Array, k: Array, v: Array, axis_name: str,
                   causal: bool = True) -> Array:
    """Blockwise ring attention over the sequence-sharded `axis_name`.

    q, k, v: (batch, seq_local, heads, head_dim) — this device's sequence
    block; the global sequence is the concatenation of blocks in mesh-axis
    order. Returns this device's (batch, seq_local, heads, head_dim) output,
    equal (up to float reassociation) to slicing full `attention` over the
    gathered sequence.

    Ring step i processes the K/V block originating at device
    `(idx - i) mod n` while `ppermute` forwards the in-flight block to the
    right neighbor; the online softmax state (running max m, normalizer l,
    unnormalized out o) makes the result order-independent.
    """
    n = lax.psum(1, axis_name)
    idx = lax.axis_index(axis_name)
    b, t, h, d = q.shape
    scale = 1.0 / jnp.sqrt(jnp.float32(d))
    q32 = q.astype(jnp.float32)

    qpos = idx * t + jnp.arange(t)  # global positions of this block's queries
    # K/V travel right one hop per step => step i sees the block of
    # device (idx - i) mod n.

    def step(carry, i):
        o, m, l, kb, vb = carry
        src = (idx - i) % n
        s = jnp.einsum("bqhd,bkhd->bhqk", q32, kb.astype(jnp.float32)) * scale
        if causal:
            kpos = src * t + jnp.arange(t)
            mask = qpos[:, None] >= kpos[None, :]        # (tq, tk)
            s = jnp.where(mask[None, None], s, _NEG)
            valid = mask[None, None]
        else:
            valid = jnp.ones(s.shape, bool)
        m_new = jnp.maximum(m, s.max(axis=-1, keepdims=True))
        # Explicitly zero masked entries: when an entire block is masked,
        # exp(_NEG - _NEG) would be 1 and corrupt the normalizer.
        p = jnp.where(valid, jnp.exp(s - m_new), 0.0)
        alpha = jnp.exp(m - m_new)
        l_new = l * alpha + p.sum(axis=-1, keepdims=True)
        # o layout is (b, t, h, d); alpha is (b, h, t, 1) -> align axes
        alpha_o = alpha[..., 0].transpose(0, 2, 1)[..., None]  # (b, t, h, 1)
        o_new = o * alpha_o + jnp.einsum(
            "bhqk,bkhd->bqhd", p, vb.astype(jnp.float32))
        perm = [(j, (j + 1) % n) for j in range(n)]
        kb = lax.ppermute(kb, axis_name, perm)
        vb = lax.ppermute(vb, axis_name, perm)
        return (o_new, m_new, l_new, kb, vb), None

    # The scan carry must have the same shard_map variance type as the
    # ppermute outputs; deriving the init from q (a zero-valued scalar that
    # carries q's variance) handles any enclosing mesh (dp, sp, ...) without
    # naming axes here.
    zq = q32.sum() * 0.0
    o0 = jnp.zeros((b, t, h, d), jnp.float32) + zq
    m0 = jnp.full((b, h, t, 1), _NEG) + zq
    l0 = jnp.zeros((b, h, t, 1), jnp.float32) + zq
    (o, m, l, _, _), _ = lax.scan(step, (o0, m0, l0, k, v), jnp.arange(n))
    l_o = l[..., 0].transpose(0, 2, 1)[..., None]  # (b, t, h, 1)
    return (o / jnp.maximum(l_o, 1e-30)).astype(q.dtype)
