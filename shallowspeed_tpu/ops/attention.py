"""Attention ops — full softmax attention, ring attention, Ulysses.

The reference has no attention anywhere (SURVEY §2: the model zoo is an
attention-free MLP, `/root/reference/shallowspeed/layers.py:236-270`), so this
module is a capability *extension*: long-context sequence/context parallelism
is first-class in this framework, built the TPU way:

- `attention`: plain batched multi-head attention, one fused XLA program —
  two MXU einsums around a VPU softmax. The single-device reference
  semantics for the ring variant.
- `ring_attention`: blockwise attention over a sequence-sharded mesh axis.
  Each device owns one sequence block of Q/K/V; K/V blocks rotate around the
  ring with `lax.ppermute` (one ICI neighbor hop per step) while each device
  accumulates its queries' attention with an online-softmax running
  (max, sum, out) state — numerically identical (up to fp reorder) to full
  attention over the gathered sequence, with O(T_local) memory and
  compute/communication overlap (the ppermute of step i+1's block overlaps
  the einsums of step i under XLA's latency-hiding scheduler).
- `ulysses_attention`: DeepSpeed-Ulysses-style all-to-all sequence
  parallelism over the same sequence-sharded axis. Two `lax.all_to_all`s
  re-shard (seq-sharded, all heads) -> (all seq, head-sharded) so each
  device runs plain full attention for its head subset, then the reverse
  all-to-all restores sequence sharding. Requires heads % axis_size == 0;
  comm volume is O(T·d/n) per device per all-to-all (vs the ring's n
  ppermute hops of K/V) and the attention itself is the single fused XLA
  program — the better choice when heads >= devices and T is moderate.

All three accept **GQA-shaped inputs natively**: k/v may carry
`n_kv_heads < n_heads` heads and repeated K/V is never materialized —
the score einsum groups query heads over the shared kv head. For the
ring this also shrinks the rotating K/V blocks (ICI traffic) by the
group factor; for Ulysses it shrinks the k/v all-to-alls the same way
(requires n_kv_heads % axis_size == 0).

All three accept `window > 0` — sliding-window (local) attention with
identical semantics everywhere: position i sees keys [i-window+1, i].

All are differentiable with `jax.grad` (the transformer family uses JAX
autodiff as its autograd, unlike the MLP family's hand-written VJPs that
mirror the reference's manual backprop layer).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import Array, lax

from shallowspeed_tpu.analysis.findings import suppress

# plain float, NOT jnp.float32: a module-level jnp constant would
# initialize the XLA backend at import time, which forbids a later
# `jax.distributed.initialize` (multi-controller runs import this
# package before calling `distributed.initialize`)
_NEG = -1e30

# Intentional `analysis` finding (dtype-promotion, MEDIUM): under bf16
# compute the attention probabilities round-trip f32->bf16->f32 once per
# block — softmax emits f32 (stability contract, see `attention`), the
# AV matmul consumes `p.astype(v.dtype)` (the MXU pass), and the
# backward needs the f32 probabilities again. The pair is the transpose
# of the primal's deliberate downcast, not a dead cast to remove: both
# endpoints are load-bearing dtypes. The match is ANCHORED to rank-5
# values — the grouped (b, kvh, g, q, k) probability tensor — so this
# suppression cannot mask, e.g., a reintroduction of the dead rank-1
# norm-scale round trips `cast_params` fixed in the same round.
suppress("dtype-promotion", match="round-trip convert chain "
         "float32->bfloat16->float32 on a rank-5",
         reason="attention-probability cast pair: softmax is f32 by the "
                "score-path stability contract, the AV matmul runs bf16 "
                "on the MXU, and the backward reuses the f32 "
                "probabilities — the round trip IS the mixed-precision "
                "boundary (ops/attention.py)")


def _group(q: Array, kvh: int):
    """(B, T, H, D) -> (B, T, Hkv, G, D): split query heads into GQA
    groups over the kv head they share (head h uses kv head h // G)."""
    b, t, h, d = q.shape
    return q.reshape(b, t, kvh, h // kvh, d)


def attention(q: Array, k: Array, v: Array, causal: bool = True,
              window: int = 0, dropout: float = 0.0,
              dropout_key=None) -> Array:
    """Multi-head scaled-dot-product attention.

    q: (batch, seq, heads, head_dim); k, v: (batch, seq, kv_heads,
    head_dim) with kv_heads | heads (kv_heads < heads = native GQA).
    Returns (batch, seq, heads, head_dim). With `causal`, position i
    attends to positions <= i; `window > 0` additionally restricts
    attention to the last `window` positions (sliding-window / local
    attention, Mistral-style: position i sees [i - window + 1, i]).

    `dropout`/`dropout_key`: ATTENTION-PROBABILITY dropout (the classic
    pre-AV-matmul mask) — active only when both are set; inverted
    scaling keeps the expectation. This exists only on this plain
    substrate: the fused flash kernels and the resharded ring/ulysses
    paths deliberately reject it (`cfg.attn_dropout` guards at config
    time), because a probability mask would have to materialize inside
    the fused/streamed score blocks.

    Mixed-precision safe: scores accumulate in float32 on the MXU
    (`preferred_element_type`) and the softmax runs in float32 regardless
    of the input dtype; only the probability @ V matmul runs in the input
    dtype. With float32 inputs every cast is a no-op.
    """
    b, tq, h, d = q.shape
    kvh = k.shape[2]
    assert h % kvh == 0, (h, kvh)
    scale = 1.0 / jnp.sqrt(jnp.float32(d))
    qg = _group(q, kvh)
    s = jnp.einsum("bqhgd,bkhd->bhgqk", qg, k,
                   preferred_element_type=jnp.float32) * scale
    if causal or window > 0:
        tk = k.shape[1]
        iq, ik = jnp.arange(tq)[:, None], jnp.arange(tk)[None, :]
        mask = iq >= ik if causal else jnp.ones((tq, tk), bool)
        if window > 0:
            mask = mask & (ik > iq - window)
        s = jnp.where(mask, s, _NEG)
    p = jax.nn.softmax(s, axis=-1)
    if dropout > 0.0 and dropout_key is not None:
        keep = 1.0 - dropout
        dmask = jax.random.bernoulli(dropout_key, keep, p.shape)
        p = jnp.where(dmask, p / keep, 0.0)
    out = jnp.einsum("bhgqk,bkhd->bqhgd", p.astype(v.dtype), v,
                     preferred_element_type=jnp.float32)
    return out.reshape(b, tq, h, d).astype(q.dtype)


attention.supports_gqa = True
attention.supports_window = True
attention.supports_prob_dropout = True


def ulysses_attention(q: Array, k: Array, v: Array, axis_name: str,
                      causal: bool = True, window: int = 0,
                      use_flash: bool = False) -> Array:
    """All-to-all (Ulysses) attention over the sequence-sharded `axis_name`.

    q, k, v: (batch, seq_local, heads, head_dim) — this device's sequence
    block, same contract as `ring_attention` (k/v may carry fewer GQA kv
    heads; then n_kv_heads % axis_size == 0 is required). Returns this
    device's (batch, seq_local, heads, head_dim) output, equal (up to
    float reassociation) to slicing full `attention` over the gathered
    sequence.

    The first all-to-all turns the sequence sharding into a *head* sharding
    (each device receives every sequence block for heads
    [idx*h/n, (idx+1)*h/n)); `tiled=True` concatenates received blocks in
    mesh-axis order, so the gathered sequence axis is already in global
    order and the plain causal/window mask is correct. Under GQA the head
    split preserves group structure: device s's query heads
    [s*h/n, (s+1)*h/n) are exactly the groups of its kv heads
    [s*kvh/n, (s+1)*kvh/n). After local full attention, the reverse
    all-to-all restores sequence sharding.

    `use_flash` swaps the local attention for the fused Pallas flash
    kernel (`ops/flash_attention.py`): because each device holds the FULL
    gathered sequence for its head subset, the kernel's standard
    causal/window mask applies unchanged — sequence parallelism, sliding
    windows, GQA, and the flash kernel all compose with no kernel
    modifications (unlike the ring formulation, which needs cross-block
    position-offset masking inside its online-softmax loop).
    """
    n = lax.psum(1, axis_name)
    h, kvh = q.shape[2], k.shape[2]
    assert h % n == 0, (
        f"ulysses_attention needs heads ({h}) divisible by the "
        f"'{axis_name}' axis size ({n}); use ring_attention otherwise")
    assert kvh % n == 0, (
        f"ulysses_attention with GQA needs kv_heads ({kvh}) divisible by "
        f"the '{axis_name}' axis size ({n}); use ring_attention otherwise")

    def gather_seq(x):  # (b, t/n, h, d) -> (b, t, h/n, d)
        return lax.all_to_all(x, axis_name, split_axis=2, concat_axis=1,
                              tiled=True)

    if use_flash:
        from shallowspeed_tpu.ops.flash_attention import flash_attention

        o = flash_attention(gather_seq(q), gather_seq(k), gather_seq(v),
                            causal=causal, window=window)
    else:
        o = attention(gather_seq(q), gather_seq(k), gather_seq(v),
                      causal=causal, window=window)
    # (b, t, h/n, d) -> (b, t/n, h, d)
    return lax.all_to_all(o, axis_name, split_axis=1, concat_axis=2,
                          tiled=True)


ulysses_attention.supports_gqa = True
ulysses_attention.supports_window = True


def ring_attention(q: Array, k: Array, v: Array, axis_name: str,
                   causal: bool = True, window: int = 0) -> Array:
    """Blockwise ring attention over the sequence-sharded `axis_name`.

    q, k, v: (batch, seq_local, heads, head_dim) — this device's sequence
    block; the global sequence is the concatenation of blocks in mesh-axis
    order (k/v may carry fewer GQA kv heads — the rotating blocks then
    shrink by the group factor). Returns this device's (batch, seq_local,
    heads, head_dim) output, equal (up to float reassociation) to slicing
    full `attention` over the gathered sequence.

    Ring step i processes the K/V block originating at device
    `(idx - i) mod n` while `ppermute` forwards the in-flight block to the
    right neighbor; the online softmax state (running max m, normalizer l,
    unnormalized out o) makes the result order-independent. `window > 0`
    masks by global positions, so sliding windows compose with sequence
    sharding unchanged (blocks entirely outside every query's window
    contribute zero via the masked online-softmax update).
    """
    n = lax.psum(1, axis_name)
    idx = lax.axis_index(axis_name)
    b, t, h, d = q.shape
    kvh = k.shape[2]
    assert h % kvh == 0, (h, kvh)
    g = h // kvh
    scale = 1.0 / jnp.sqrt(jnp.float32(d))
    q32 = _group(q.astype(jnp.float32), kvh)  # (b, t, kvh, g, d)

    qpos = idx * t + jnp.arange(t)  # global positions of this block's queries
    # K/V travel right one hop per step => step i sees the block of
    # device (idx - i) mod n.

    def step(carry, i):
        o, m, l, kb, vb = carry
        src = (idx - i) % n
        s = jnp.einsum("bqhgd,bkhd->bhgqk", q32,
                       kb.astype(jnp.float32)) * scale
        kpos = src * t + jnp.arange(t)
        if causal or window > 0:
            mask = (qpos[:, None] >= kpos[None, :] if causal
                    else jnp.ones((t, t), bool))
            if window > 0:
                mask = mask & (kpos[None, :] > qpos[:, None] - window)
            s = jnp.where(mask[None, None, None], s, _NEG)
            valid = jnp.broadcast_to(mask[None, None, None], s.shape)
        else:
            valid = jnp.ones(s.shape, bool)
        m_new = jnp.maximum(m, s.max(axis=-1, keepdims=True))
        # Explicitly zero masked entries: when an entire block is masked,
        # exp(_NEG - _NEG) would be 1 and corrupt the normalizer.
        p = jnp.where(valid, jnp.exp(s - m_new), 0.0)
        alpha = jnp.exp(m - m_new)
        l_new = l * alpha + p.sum(axis=-1, keepdims=True)
        # o layout is (b, t, kvh, g, d); alpha is (b, kvh, g, t, 1) -> align
        alpha_o = alpha[..., 0].transpose(0, 3, 1, 2)[..., None]
        o_new = o * alpha_o + jnp.einsum(
            "bhgqk,bkhd->bqhgd", p, vb.astype(jnp.float32))
        perm = [(j, (j + 1) % n) for j in range(n)]
        kb = lax.ppermute(kb, axis_name, perm)
        vb = lax.ppermute(vb, axis_name, perm)
        return (o_new, m_new, l_new, kb, vb), None

    # The scan carry must have the same shard_map variance type as the
    # ppermute outputs; deriving the init from q (a zero-valued scalar that
    # carries q's variance) handles any enclosing mesh (dp, sp, ...) without
    # naming axes here.
    zq = q32.sum() * 0.0
    o0 = jnp.zeros((b, t, kvh, g, d), jnp.float32) + zq
    m0 = jnp.full((b, kvh, g, t, 1), _NEG) + zq
    l0 = jnp.zeros((b, kvh, g, t, 1), jnp.float32) + zq
    (o, m, l, _, _), _ = lax.scan(step, (o0, m0, l0, k, v), jnp.arange(n))
    l_o = l[..., 0].transpose(0, 3, 1, 2)[..., None]  # (b, t, kvh, g, 1)
    return (o / jnp.maximum(l_o, 1e-30)).reshape(b, t, h, d).astype(q.dtype)


ring_attention.supports_gqa = True
ring_attention.supports_window = True
