"""Pure stateless ops — the L1 "kernel" layer.

Mirrors the capability of the reference's NumPy ops layer
(`/root/reference/shallowspeed/functional.py:1-44`) with `jax.numpy`
implementations that XLA jit-compiles onto the TPU MXU/VPU. All functions are
pure and shape-polymorphic, so they can be traced once per shape and fused by
XLA; the hand-written gradients are kept (they define the manual-autograd
contract of the framework) and are cross-checked against `jax.vjp` in
`tests/test_functional.py`.

Semantics notes (capability parity, verified against the reference):
- `softmax` subtracts the *global* max of the block (not per-row) and adds
  1e-7 to the denominator (`functional.py:24-27` in the reference).
- `mse_loss` / `mse_loss_grad` divide by the caller-supplied **global** batch
  size (`functional.py:38-44`), which is the invariant that makes
  sum-accumulation over microbatches and sum-reduction over DP replicas equal
  the exact global-batch gradient.
"""

from __future__ import annotations

import jax.numpy as jnp
from jax import Array


def relu(x: Array) -> Array:
    """max(x, 0). Reference: `functional.py:4-5`."""
    return jnp.maximum(x, 0.0)


def relu_grad(dout: Array, bitmask: Array) -> Array:
    """VJP of relu given the cached `x > 0` bitmask. Reference: `functional.py:8-10`."""
    return dout * bitmask


def linear(x: Array, weight: Array, bias: Array) -> Array:
    """y = x @ W.T + b — the MXU hot path. Reference: `functional.py:13-17`.

    Weight layout is (out_dims, in_dims) to match the framework's parameter
    convention; XLA folds the transpose into the matmul tiling.
    """
    return x @ weight.T + bias


def linear_grad(dout: Array, x: Array, weight: Array):
    """VJP of `linear`: returns (dx, dW, db). Reference: `functional.py:20-21`.

    Two MXU matmuls plus a VPU row-reduction; XLA schedules all three from one
    fused backward when jitted.
    """
    return dout @ weight, dout.T @ x, dout.sum(axis=0, keepdims=True)


def softmax(x: Array) -> Array:
    """Row softmax with global max-shift + 1e-7 denominator epsilon.

    Reference: `functional.py:24-27` (the global — not per-row — max subtraction
    and the epsilon are part of the reference's numerics and kept for
    equivalence testing).
    """
    shifted = jnp.exp(x - jnp.max(x))
    return shifted / (shifted.sum(axis=1, keepdims=True) + 1e-7)


def softmax_grad(dout: Array, x: Array) -> Array:
    """VJP of `softmax` recomputed from the cached *input* (rematerialisation).

    Reference: `functional.py:30-35`. Recomputing the forward here is the
    FLOPs-for-HBM trade TPUs favour; under jit XLA fuses the recompute into the
    backward so no extra HBM round-trip occurs.
    """
    out = softmax(x)
    g = out * dout
    return g - out * g.sum(axis=-1, keepdims=True)


def mse_loss(pred: Array, target: Array, batch_size: int) -> Array:
    """Sum of squared errors divided by the *global* batch size.

    Reference: `functional.py:38-40`. (The reference never evaluates the loss
    during training — only its gradient — but exposes the value; we keep both.)
    """
    assert pred.shape == target.shape, (pred.shape, target.shape)
    return ((target - pred) ** 2).sum() / batch_size


def mse_loss_grad(pred: Array, target: Array, batch_size: int) -> Array:
    """d/dpred of `mse_loss`. Reference: `functional.py:43-44`.

    Dividing by the global batch size (not the microbatch size) makes
    microbatch-sum + DP-psum accumulation exactly equal the serial
    global-batch gradient.
    """
    return -2.0 * (target - pred) / batch_size
