"""Pallas blocked matmul — the narrow-K mitigation.

XLA/Mosaic's default lowering of bf16 matmuls with K ~ 1024 runs at
~1/8 of peak on v5e (measured in BASELINE.md: (16384,1024)@(1024,4096)
at ~21 TFLOP/s vs 159-170 at K>=2048 — the same op, wider). The
reference has no analogue (its matmuls are NumPy BLAS calls,
`/root/reference/shallowspeed/functional.py`); this kernel exists
purely to claim back the MXU on narrow-K shapes.

Classic 3-D-grid formulation: (M/bm, N/bn, K/bk) programs, an f32 VMEM
accumulator per (i, j) tile, K innermost so the accumulator stays
resident while K-blocks stream through. `jnp.dot` inside the kernel
with `preferred_element_type=f32` drives the MXU directly with our
block shapes instead of Mosaic's narrow-K choice.
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu


def _mm_kernel(x_ref, y_ref, o_ref, acc_ref, *, nk: int):
    k = pl.program_id(2)

    @pl.when(k == 0)
    def _init():
        acc_ref[...] = jnp.zeros_like(acc_ref)

    acc_ref[...] += jnp.dot(x_ref[...], y_ref[...],
                            preferred_element_type=jnp.float32)

    @pl.when(k == nk - 1)
    def _flush():
        o_ref[...] = acc_ref[...].astype(o_ref.dtype)


def _interpret_default() -> bool:
    return jax.default_backend() != "tpu"


# ------------------------------------------------ fused-dequant matmul
#
# Quantized WEIGHT storage (round 14, ROADMAP item 1): decode is
# HBM-bound on the parameter sweep, so int8 (or fp8-e4m3) weights with
# per-out-channel f32 scales halve-or-better the bytes behind
# `serving/cache.param_read_bytes`. The trap is dequantizing wrong: a
# `(wq * scale).astype(f32)` materializes a FULL-SIZE dequantized copy
# of the weight — the exact HBM traffic the storage was meant to
# remove. The contract here is the fused form, proved statically by
# the analysis `dequant-fusion` rule over the traced decode tick.


def dequant_matmul(x, wq, ws, *, compute_dtype=None):
    """x (..., K) @ quantized wq (K, N) with per-out-channel f32 scales
    ws (N,), the dequant FUSED into the matmul:

    - wq's VALUES are cast to the compute dtype inside the dot. That is
      a value cast, not a dequant — int8 integers and e4m3 floats are
      both exactly representable in bf16/f32 — and XLA folds it into
      the operand load, so HBM reads stay 1 byte/element.
    - accumulation is f32 (`preferred_element_type`), matching every
      other MXU dot in the repo.
    - the scale multiplies the f32 ACCUMULATOR (shape (..., N)), never
      the weight: no (K, N) dequantized buffer ever exists. The
      per-out-channel scale is constant along the contraction axis,
      which is what makes this reassociation exact.

    Returns (..., N) in x's dtype. The analysis `dequant-fusion` rule
    walks consumers of every int8/fp8 weight upcast and flags any
    full-weight-size elementwise use — this function is its clean
    fixture."""
    cdt = compute_dtype or x.dtype
    acc = jax.lax.dot_general(
        x.astype(cdt), wq.astype(cdt),
        (((x.ndim - 1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32)
    return (acc * ws.astype(jnp.float32)).astype(x.dtype)


@partial(jax.jit,
         static_argnames=("bm", "bk", "bn", "out_dtype", "interpret"))
def blocked_matmul(x, y, *, bm: int = 512, bk: int = 512, bn: int = 1024,
                   out_dtype=None, interpret: bool | None = None):
    """x (M, K) @ y (K, N) with explicit (bm, bk, bn) MXU tiling and an
    f32 accumulator. Shapes must divide by the blocks (the training use
    sites have power-of-two dims; no padding path here). Keep
    bm*bn*4 + bm*bk*2 + bk*bn*2 well under the 16MB scoped-VMEM ceiling
    (double buffering roughly doubles the block traffic)."""
    if interpret is None:
        interpret = _interpret_default()
    m, k = x.shape
    k2, n = y.shape
    assert k == k2, (x.shape, y.shape)
    bm, bk, bn = min(bm, m), min(bk, k), min(bn, n)
    assert m % bm == 0 and k % bk == 0 and n % bn == 0, (
        f"({m},{k})@({k},{n}) must divide by blocks ({bm},{bk},{bn})")
    out_dtype = out_dtype or x.dtype
    nk = k // bk
    kw = {}
    if not interpret:
        kw["compiler_params"] = pltpu.CompilerParams(
            dimension_semantics=("parallel", "parallel", "arbitrary"))
    return pl.pallas_call(
        partial(_mm_kernel, nk=nk),
        grid=(m // bm, n // bn, nk),
        in_specs=[
            pl.BlockSpec((bm, bk), lambda i, j, kk: (i, kk)),
            pl.BlockSpec((bk, bn), lambda i, j, kk: (kk, j)),
        ],
        out_specs=pl.BlockSpec((bm, bn), lambda i, j, kk: (i, j)),
        scratch_shapes=[pltpu.VMEM((bm, bn), jnp.float32)],
        out_shape=jax.ShapeDtypeStruct((m, n), out_dtype),
        interpret=interpret,
        **kw,
    )(x, y)


# --------------------------------------------- fp8-e4m3 training matmul
#
# ROADMAP item 5's forward path (round 16): fp8-e4m3 storage for the
# forward matmul's operands, with the same fused-dequant discipline as
# `dequant_matmul` — the scale product lands on the f32 ACCUMULATOR,
# never on an operand-sized buffer. The backward is a straight-through
# estimator written by hand: naive autodiff through the quantization
# casts would round-trip the COTANGENTS through e4m3 (a second
# narrowing with no rescale — exactly what the analysis
# `fp8-double-rounding` rule flags), so the custom VJP keeps gradients
# f32 end-to-end and re-uses the stored fp8 operands only inside f32-
# accumulated dots. The `fp8_train` analysis target proves all of this
# statically on the traced step.

E4M3_MAX = 448.0  # ml_dtypes.finfo(float8_e4m3fn).max
E4M3_TINY = 2.0 ** -9  # smallest e4m3 subnormal (1 * 2^-9)


def _check_fp8_operands(x, w):
    """fp8_dense's shape contract as a typed error (the repo's
    config-validation convention): the hand VJP contracts the batch
    axis for dw, so only 2-D activations/weights are expressible."""
    if x.ndim != 2 or w.ndim != 2:
        raise ValueError(
            f"fp8_dense takes 2-D operands x (B, K) @ w (K, N); got "
            f"x.shape={tuple(x.shape)}, w.shape={tuple(w.shape)} — "
            f"reshape (..., K) activations to (-1, K) at the call site")
    if x.shape[1] != w.shape[0]:
        raise ValueError(
            f"fp8_dense contraction mismatch: x (B, K={x.shape[1]}) @ "
            f"w (K={w.shape[0]}, N)")


def fp8_clamp_stats(x, scale):
    """Traced per-tensor clamp statistics for one activation quantize —
    the numerics pack's raw ingredients, computed on the SAME (x,
    scale) pair `fp8_quantize` sees so the fractions describe exactly
    what the dot consumed:

    - overflow: fraction of elements saturated by the ±E4M3_MAX clip
      (a too-SMALL delayed scale — amax history collapsed or lagging a
      range expansion);
    - underflow: fraction of NONZERO elements that round to zero in
      e4m3 (|x/scale| below half the smallest subnormal — a too-LARGE
      scale flushing real signal; exact zeros are excluded so ReLU
      sparsity does not read as underflow).

    Returns two f32 scalars; a handful of VPU ops per call, designed to
    ride the compiled step under the health pack's zero-new-executables
    contract. The weight side is deliberately not measured: its
    just-in-time per-out-channel scale makes saturation impossible by
    construction."""
    y = jnp.abs(x.astype(jnp.float32)) / scale
    overflow = jnp.mean((y > E4M3_MAX).astype(jnp.float32))
    nz = y > 0.0
    under = jnp.logical_and(nz, y < 0.5 * E4M3_TINY)
    denom = jnp.maximum(jnp.sum(nz.astype(jnp.float32)), 1.0)
    underflow = jnp.sum(under.astype(jnp.float32)) / denom
    return overflow, underflow


def fp8_quantize(x, scale):
    """`x / scale`, saturated to the e4m3 range and rounded once into
    fp8 storage. The clip is what makes the convert provably in-range
    for the analysis `range-safety` rule; the divide is the rescale
    that pairs the quantized lineage to `scale` for `scale-consistency`
    (and resets the rounding state for `fp8-double-rounding`)."""
    y = x.astype(jnp.float32) / scale
    return jnp.clip(y, -E4M3_MAX, E4M3_MAX).astype(jnp.float8_e4m3fn)


def _w_scale(w):
    """Just-in-time per-out-channel weight scale. `stop_gradient`: the
    scale is quantization bookkeeping, not a trainable path."""
    amax = jnp.max(jnp.abs(w.astype(jnp.float32)), axis=0)
    return jax.lax.stop_gradient(jnp.maximum(amax / E4M3_MAX, 1e-12))


@jax.custom_vjp
def fp8_dense(x, w, sx):
    """x (B, K) @ w (K, N), both quantized to fp8-e4m3 for the dot:
    `x` with the DELAYED per-tensor scale `sx` (from the caller's amax
    history — this step's stats only feed the NEXT step's scale), `w`
    with a just-in-time per-out-channel scale. f32 accumulation; the
    dequant `* (sx * sw)` is reassociated onto the accumulator (both
    scales are constant along the contraction axis). Returns (..., N)
    f32. 2-D activations only (the hand VJP contracts the batch
    axis for dw)."""
    _check_fp8_operands(x, w)
    sw = _w_scale(w)
    acc = jax.lax.dot_general(
        fp8_quantize(x, sx), fp8_quantize(w, sw),
        (((1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32)
    return acc * (sx * sw)


def _fp8_dense_fwd(x, w, sx):
    _check_fp8_operands(x, w)
    sw = _w_scale(w)
    xq, wq = fp8_quantize(x, sx), fp8_quantize(w, sw)
    acc = jax.lax.dot_general(
        xq, wq, (((1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32)
    return acc * (sx * sw), (xq, wq, sx, sw)


def _fp8_dense_bwd(res, g):
    """Straight-through estimator: quantization treated as identity, so
    dx = g @ w^T and dw = x^T @ g, computed FROM the stored fp8
    operands with every dequant on an f32 accumulator:

    - dx: the cotangent arrives pre-multiplied by `sw` (the analysis
      prover's "cotangent-scaled" form — `wq`'s scale rides the other
      dot operand), and `sx` dequantizes the accumulator.
    - dw: `xq`'s dequant by `sx` is reassociated onto the accumulator
      (`sx` is per-tensor, constant along every axis).
    - the scales get zero cotangents: bookkeeping, not parameters.

    Saturated elements keep their pass-through gradient (plain STE; no
    clip mask — delayed scaling keeps saturation rare by construction).
    """
    xq, wq, sx, sw = res
    g = g.astype(jnp.float32)
    dx = jax.lax.dot_general(
        g * sw, wq, (((1,), (1,)), ((), ())),
        preferred_element_type=jnp.float32) * sx
    dw = jax.lax.dot_general(
        xq, g, (((0,), (0,)), ((), ())),
        preferred_element_type=jnp.float32) * sx
    return dx, dw, jnp.zeros_like(sx)


fp8_dense.defvjp(_fp8_dense_fwd, _fp8_dense_bwd)
