"""Pallas blocked matmul — the narrow-K mitigation.

XLA/Mosaic's default lowering of bf16 matmuls with K ~ 1024 runs at
~1/8 of peak on v5e (measured in BASELINE.md: (16384,1024)@(1024,4096)
at ~21 TFLOP/s vs 159-170 at K>=2048 — the same op, wider). The
reference has no analogue (its matmuls are NumPy BLAS calls,
`/root/reference/shallowspeed/functional.py`); this kernel exists
purely to claim back the MXU on narrow-K shapes.

Classic 3-D-grid formulation: (M/bm, N/bn, K/bk) programs, an f32 VMEM
accumulator per (i, j) tile, K innermost so the accumulator stays
resident while K-blocks stream through. `jnp.dot` inside the kernel
with `preferred_element_type=f32` drives the MXU directly with our
block shapes instead of Mosaic's narrow-K choice.
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu


def _mm_kernel(x_ref, y_ref, o_ref, acc_ref, *, nk: int):
    k = pl.program_id(2)

    @pl.when(k == 0)
    def _init():
        acc_ref[...] = jnp.zeros_like(acc_ref)

    acc_ref[...] += jnp.dot(x_ref[...], y_ref[...],
                            preferred_element_type=jnp.float32)

    @pl.when(k == nk - 1)
    def _flush():
        o_ref[...] = acc_ref[...].astype(o_ref.dtype)


def _interpret_default() -> bool:
    return jax.default_backend() != "tpu"


# ------------------------------------------------ fused-dequant matmul
#
# Quantized WEIGHT storage (round 14, ROADMAP item 1): decode is
# HBM-bound on the parameter sweep, so int8 (or fp8-e4m3) weights with
# per-out-channel f32 scales halve-or-better the bytes behind
# `serving/cache.param_read_bytes`. The trap is dequantizing wrong: a
# `(wq * scale).astype(f32)` materializes a FULL-SIZE dequantized copy
# of the weight — the exact HBM traffic the storage was meant to
# remove. The contract here is the fused form, proved statically by
# the analysis `dequant-fusion` rule over the traced decode tick.


def dequant_matmul(x, wq, ws, *, compute_dtype=None):
    """x (..., K) @ quantized wq (K, N) with per-out-channel f32 scales
    ws (N,), the dequant FUSED into the matmul:

    - wq's VALUES are cast to the compute dtype inside the dot. That is
      a value cast, not a dequant — int8 integers and e4m3 floats are
      both exactly representable in bf16/f32 — and XLA folds it into
      the operand load, so HBM reads stay 1 byte/element.
    - accumulation is f32 (`preferred_element_type`), matching every
      other MXU dot in the repo.
    - the scale multiplies the f32 ACCUMULATOR (shape (..., N)), never
      the weight: no (K, N) dequantized buffer ever exists. The
      per-out-channel scale is constant along the contraction axis,
      which is what makes this reassociation exact.

    Returns (..., N) in x's dtype. The analysis `dequant-fusion` rule
    walks consumers of every int8/fp8 weight upcast and flags any
    full-weight-size elementwise use — this function is its clean
    fixture."""
    cdt = compute_dtype or x.dtype
    acc = jax.lax.dot_general(
        x.astype(cdt), wq.astype(cdt),
        (((x.ndim - 1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32)
    return (acc * ws.astype(jnp.float32)).astype(x.dtype)


@partial(jax.jit,
         static_argnames=("bm", "bk", "bn", "out_dtype", "interpret"))
def blocked_matmul(x, y, *, bm: int = 512, bk: int = 512, bn: int = 1024,
                   out_dtype=None, interpret: bool | None = None):
    """x (M, K) @ y (K, N) with explicit (bm, bk, bn) MXU tiling and an
    f32 accumulator. Shapes must divide by the blocks (the training use
    sites have power-of-two dims; no padding path here). Keep
    bm*bn*4 + bm*bk*2 + bk*bn*2 well under the 16MB scoped-VMEM ceiling
    (double buffering roughly doubles the block traffic)."""
    if interpret is None:
        interpret = _interpret_default()
    m, k = x.shape
    k2, n = y.shape
    assert k == k2, (x.shape, y.shape)
    bm, bk, bn = min(bm, m), min(bk, k), min(bn, n)
    assert m % bm == 0 and k % bk == 0 and n % bn == 0, (
        f"({m},{k})@({k},{n}) must divide by blocks ({bm},{bk},{bn})")
    out_dtype = out_dtype or x.dtype
    nk = k // bk
    kw = {}
    if not interpret:
        kw["compiler_params"] = pltpu.CompilerParams(
            dimension_semantics=("parallel", "parallel", "arbitrary"))
    return pl.pallas_call(
        partial(_mm_kernel, nk=nk),
        grid=(m // bm, n // bn, nk),
        in_specs=[
            pl.BlockSpec((bm, bk), lambda i, j, kk: (i, kk)),
            pl.BlockSpec((bk, bn), lambda i, j, kk: (kk, j)),
        ],
        out_specs=pl.BlockSpec((bm, bn), lambda i, j, kk: (i, j)),
        scratch_shapes=[pltpu.VMEM((bm, bn), jnp.float32)],
        out_shape=jax.ShapeDtypeStruct((m, n), out_dtype),
        interpret=interpret,
        **kw,
    )(x, y)
