"""Distributed-correctness utilities.

Capability parity with `/root/reference/shallowspeed/utils.py:8-31` (rank-0
print, model hashing, cross-replica sync assertion), re-targeted at
single-controller JAX: "rank 0" becomes `jax.process_index() == 0`, and the
sync check hashes the per-device shards of a sharded/replicated params pytree
instead of MPI-gathering.
"""

from __future__ import annotations

import hashlib
from typing import Any

import jax
import numpy as np


def rprint(*args, **kwargs):
    """Print once per job (reference `utils.py:8-10` prints on MPI rank 0)."""
    if jax.process_index() == 0:
        print(*args, **kwargs)


def get_model_hash(params: Any) -> str:
    """SHA-1 over the concatenated per-leaf SHA-1s (reference `utils.py:13-24`)."""
    leaves = jax.tree_util.tree_leaves(params)
    combo = hashlib.sha1()
    for leaf in leaves:
        arr = np.asarray(jax.device_get(leaf))
        combo.update(hashlib.sha1(np.ascontiguousarray(arr).tobytes()).hexdigest()
                     .encode())
    return combo.hexdigest()


def assert_replicas_in_sync(params: Any) -> None:
    """Assert every device shard of a replicated params pytree is bit-identical.

    The reference gathers per-rank model hashes to root and raises on mismatch
    after training (`utils.py:27-31`, `train.py:154-155`). Under
    single-controller JAX, DP replicas are the per-device copies of arrays
    replicated over the `dp` mesh axis; we hash each addressable shard.
    """
    for leaf in jax.tree_util.tree_leaves(params):
        if not isinstance(leaf, jax.Array):
            continue
        # Group shards by the logical index they hold: replicas of the same
        # slice (e.g. dp-replicated copies of a pp shard) must be identical.
        by_slice: dict[tuple, set[str]] = {}
        for shard in leaf.addressable_shards:
            key = tuple((s.start, s.stop, s.step) for s in shard.index)
            arr = np.asarray(shard.data)
            h = hashlib.sha1(np.ascontiguousarray(arr).tobytes()).hexdigest()
            by_slice.setdefault(key, set()).add(h)
        for key, hashes in by_slice.items():
            if len(hashes) > 1:
                raise AssertionError(
                    f"DP replicas out of sync for leaf {leaf.shape} slice "
                    f"{key}: {sorted(hashes)}")


# Does this jax generation type shard_map values by varying-manual-axes
# (VMA)? Gates BOTH compat shims below: on VMA jax, `pvary_over` does the
# carry/branch typing and shard_map's default checking IS that typing; on
# pre-VMA jax, pvary has nothing to do and the old rewrite-based
# replication checker (which predates several primitives these engines
# trace) must be disabled instead.
_HAS_VMA = hasattr(jax.lax, "pcast") or hasattr(jax.lax, "pvary")


def shard_map(f=None, **kw):
    """`jax.shard_map` across API generations (drop-in for the engines'
    `partial(shard_map, mesh=..., in_specs=..., out_specs=...)` idiom).
    On pre-VMA jax, passes `check_rep=False`: the engines' programs are
    variance-typed for VMA shard_map, and the legacy replication
    rewriter rejects primitives they rely on (scan-carried ppermute
    chains and friends) with "No replication rule". The collective
    structure itself is unchanged — `analysis`'s collective rule and the
    cross-engine parity tests check it, not the legacy rewriter."""
    try:
        from jax import shard_map as _sm
    except ImportError:  # pragma: no cover
        from jax.experimental.shard_map import shard_map as _sm
    if not _HAS_VMA:
        kw.setdefault("check_rep", False)
    if f is None:
        return lambda g: _sm(g, **kw)
    return _sm(f, **kw)


def _pvary_leaf(leaf, ax: str):
    """One leaf to 'varying' over `ax`, across jax API generations:
    `lax.pcast(..., to="varying")` (newest), `lax.pvary` (the rename it
    shipped under first), or identity on pre-VMA jax — there shard_map
    has no varying-manual-axes types, so the cast has nothing to do."""
    lax = jax.lax
    if hasattr(lax, "pcast"):
        return lax.pcast(leaf, (ax,), to="varying")
    if hasattr(lax, "pvary"):
        return lax.pvary(leaf, (ax,))
    return leaf


def pvary_over(tree: Any, axes: tuple[str, ...]) -> Any:
    """Cast a pytree to 'varying' over the given shard_map mesh axes (VMA).

    Inside `shard_map`, axis-invariant constants (e.g. a zeros scan-carry
    init) and axis-varying data (e.g. outputs of `ppermute`) have different
    types; this casts the former so carries typecheck. Skips axes a leaf
    already varies over (pcast rejects those).
    """
    def cast(leaf):
        for ax in axes:
            try:
                leaf = _pvary_leaf(leaf, ax)
            except ValueError:
                pass  # already varying over this axis
        return leaf

    return jax.tree_util.tree_map(cast, tree)


# --------------------------- Megatron conjugate collectives (pre-VMA)
#
# Differentiating THROUGH an in-block `lax.psum` is only correct when
# shard_map's variance typing (VMA) is there to transpose it: on pre-VMA
# jax with `check_rep=False` the legacy rule transposes psum to psum, so
# a replicated cotangent gets summed tp times (tensor-sharded weight
# grads come out exactly tp x too large), and nothing inserts the psum
# a tp-PARTIAL cotangent needs on the way back to replicated params
# (layernorm/embedding grads come out shard-partial). Caught at runtime
# by the health pack's oracle parity (telemetry/health.py, round 7) —
# every pp x tp config trained with corrupted gradients on pre-VMA jax
# while loss-only parity tests stayed green.
#
# The fix is Megatron-LM's conjugate operator pair, as explicit
# custom-VJP ops gated on the jax generation (on VMA jax both are
# trivial — variance typing already transposes correctly):
#   tp_allreduce ("g"): psum forward, identity backward — placed after
#     row-parallel matmuls, where the forward needs the cross-shard sum
#     and the backward cotangent is already replicated.
#   tp_region_enter ("f"): identity forward, psum backward — placed
#     where the replicated residual stream enters column-parallel
#     compute, so the shard-partial cotangents are summed exactly once.

from functools import partial as _partial


@_partial(jax.custom_vjp, nondiff_argnums=(0,))
def _psum_fwd_identity_bwd(axis, x):
    return jax.lax.psum(x, axis)


def _pfib_fwd(axis, x):
    return jax.lax.psum(x, axis), None


def _pfib_bwd(axis, _res, g):
    return (g,)


_psum_fwd_identity_bwd.defvjp(_pfib_fwd, _pfib_bwd)


@_partial(jax.custom_vjp, nondiff_argnums=(0,))
def _identity_fwd_psum_bwd(axis, x):
    return x


def _ifpb_fwd(axis, x):
    return x, None


def _ifpb_bwd(axis, _res, g):
    return (jax.lax.psum(g, axis),)


_identity_fwd_psum_bwd.defvjp(_ifpb_fwd, _ifpb_bwd)


def tp_allreduce(x, axis: str = "tp"):
    """All-reduce a row-parallel partial sum over `axis` with the
    backward a tensor-parallel program needs (see block comment)."""
    if _HAS_VMA:
        return jax.lax.psum(x, axis)
    return _psum_fwd_identity_bwd(axis, x)


def tp_region_enter(x, axis: str = "tp"):
    """Mark a replicated activation's entry into column-parallel
    compute: identity forward, cotangent psum over `axis` on pre-VMA
    jax (see block comment)."""
    if _HAS_VMA:
        return x
    return _identity_fwd_psum_bwd(axis, x)
