"""Distributed-correctness utilities.

Capability parity with `/root/reference/shallowspeed/utils.py:8-31` (rank-0
print, model hashing, cross-replica sync assertion), re-targeted at
single-controller JAX: "rank 0" becomes `jax.process_index() == 0`, and the
sync check hashes the per-device shards of a sharded/replicated params pytree
instead of MPI-gathering.
"""

from __future__ import annotations

import hashlib
from typing import Any

import jax
import numpy as np


def rprint(*args, **kwargs):
    """Print once per job (reference `utils.py:8-10` prints on MPI rank 0)."""
    if jax.process_index() == 0:
        print(*args, **kwargs)


def get_model_hash(params: Any) -> str:
    """SHA-1 over the concatenated per-leaf SHA-1s (reference `utils.py:13-24`)."""
    leaves = jax.tree_util.tree_leaves(params)
    combo = hashlib.sha1()
    for leaf in leaves:
        arr = np.asarray(jax.device_get(leaf))
        combo.update(hashlib.sha1(np.ascontiguousarray(arr).tobytes()).hexdigest()
                     .encode())
    return combo.hexdigest()


def assert_replicas_in_sync(params: Any) -> None:
    """Assert every device shard of a replicated params pytree is bit-identical.

    The reference gathers per-rank model hashes to root and raises on mismatch
    after training (`utils.py:27-31`, `train.py:154-155`). Under
    single-controller JAX, DP replicas are the per-device copies of arrays
    replicated over the `dp` mesh axis; we hash each addressable shard.
    """
    for leaf in jax.tree_util.tree_leaves(params):
        if not isinstance(leaf, jax.Array):
            continue
        # Group shards by the logical index they hold: replicas of the same
        # slice (e.g. dp-replicated copies of a pp shard) must be identical.
        by_slice: dict[tuple, set[str]] = {}
        for shard in leaf.addressable_shards:
            key = tuple((s.start, s.stop, s.step) for s in shard.index)
            arr = np.asarray(shard.data)
            h = hashlib.sha1(np.ascontiguousarray(arr).tobytes()).hexdigest()
            by_slice.setdefault(key, set()).add(h)
        for key, hashes in by_slice.items():
            if len(hashes) > 1:
                raise AssertionError(
                    f"DP replicas out of sync for leaf {leaf.shape} slice "
                    f"{key}: {sorted(hashes)}")


def pvary_over(tree: Any, axes: tuple[str, ...]) -> Any:
    """Cast a pytree to 'varying' over the given shard_map mesh axes (VMA).

    Inside `shard_map`, axis-invariant constants (e.g. a zeros scan-carry
    init) and axis-varying data (e.g. outputs of `ppermute`) have different
    types; this casts the former so carries typecheck. Skips axes a leaf
    already varies over (pcast rejects those).
    """
    def cast(leaf):
        for ax in axes:
            try:
                leaf = jax.lax.pcast(leaf, (ax,), to="varying")
            except ValueError:
                pass  # already varying over this axis
        return leaf

    return jax.tree_util.tree_map(cast, tree)
