"""Distributed-correctness utilities.

Capability parity with `/root/reference/shallowspeed/utils.py:8-31` (rank-0
print, model hashing, cross-replica sync assertion), re-targeted at
single-controller JAX: "rank 0" becomes `jax.process_index() == 0`, and the
sync check hashes the per-device shards of a sharded/replicated params pytree
instead of MPI-gathering.
"""

from __future__ import annotations

import hashlib
from typing import Any

import jax
import numpy as np


def rprint(*args, **kwargs):
    """Print once per job (reference `utils.py:8-10` prints on MPI rank 0)."""
    if jax.process_index() == 0:
        print(*args, **kwargs)


def get_model_hash(params: Any) -> str:
    """SHA-1 over the concatenated per-leaf SHA-1s (reference `utils.py:13-24`)."""
    leaves = jax.tree_util.tree_leaves(params)
    combo = hashlib.sha1()
    for leaf in leaves:
        arr = np.asarray(jax.device_get(leaf))
        combo.update(hashlib.sha1(np.ascontiguousarray(arr).tobytes()).hexdigest()
                     .encode())
    return combo.hexdigest()


def assert_replicas_in_sync(params: Any) -> None:
    """Assert every device shard of a replicated params pytree is bit-identical.

    The reference gathers per-rank model hashes to root and raises on mismatch
    after training (`utils.py:27-31`, `train.py:154-155`). Under
    single-controller JAX, DP replicas are the per-device copies of arrays
    replicated over the `dp` mesh axis; we hash each addressable shard.
    """
    for leaf in jax.tree_util.tree_leaves(params):
        if not isinstance(leaf, jax.Array):
            continue
        leaf_hashes = []
        for shard in leaf.addressable_shards:
            arr = np.asarray(shard.data)
            leaf_hashes.append(
                hashlib.sha1(np.ascontiguousarray(arr).tobytes()).hexdigest())
        # all shards holding the same logical slice must agree; for fully
        # replicated leaves every shard is the same slice
        if len(set(leaf_hashes)) > 1 and _is_fully_replicated(leaf):
            raise AssertionError(
                f"DP replicas out of sync for leaf {leaf.shape}: {leaf_hashes}")


def _is_fully_replicated(arr: jax.Array) -> bool:
    try:
        return arr.is_fully_replicated
    except AttributeError:  # older jax
        return False
