"""fp8-e4m3 forward-matmul training — ROADMAP item 5's first rung.

A deliberately minimal trainer whose every numerics decision is the
one the static prover certifies (`analysis --target fp8_train`):

- **forward** matmuls run in fp8-e4m3 via `ops.matmul.fp8_dense` —
  activations quantized with a DELAYED per-tensor scale, weights with a
  just-in-time per-out-channel scale, dequant fused onto the f32
  accumulator (the `dequant_matmul` discipline, extended to training).
- **delayed scaling** (the Transformer-Engine recipe): this step's
  activation absmaxes only feed the NEXT steps' scales, through a
  rolling per-layer amax history carried in the step like optimizer
  state. The history rides the health pack (`fp8_amax` / `fp8_scale`)
  so a drifting scale is visible at every log point, next to grad
  norms.
- **backward** is a hand straight-through VJP: gradients stay f32
  end-to-end (autodiff through the quantization casts would re-round
  cotangents through e4m3 — the exact `fp8-double-rounding` bug
  class), and parameters/optimizer state are f32 master copies.

The runtime acceptance for longer runs is the PR-5 `attrib_mxu_frac`
waterfall plus oracle loss-parity; what lives here is the statically
certified step: the analysis gate proves no double rounding, f32
accumulation everywhere, scale pairing on both dot sides (including
the VJP), and in-range converts, before a long run is burned.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from shallowspeed_tpu.ops.matmul import E4M3_MAX, fp8_dense
from shallowspeed_tpu.telemetry.health import (grad_health, note_step,
                                               update_health)

tree_map = jax.tree_util.tree_map

# rolling absmax window (steps) behind the delayed activation scale
AMAX_HISTORY = 16


def init_fp8_mlp(sizes, seed: int = 0) -> dict:
    """f32 master params for a dense ReLU MLP: He-scaled weights, zero
    biases — `sizes` is [d_in, hidden..., d_out]."""
    rng = np.random.default_rng(seed)
    layers = []
    for fan_in, fan_out in zip(sizes[:-1], sizes[1:]):
        w = rng.standard_normal((fan_in, fan_out)) * np.sqrt(2.0 / fan_in)
        layers.append({"W": jnp.asarray(w, jnp.float32),
                       "b": jnp.zeros((fan_out,), jnp.float32)})
    return {"layers": layers}


class Fp8TrainEngine:
    """Single-device fp8 forward-matmul trainer (MSE regression head —
    no exp/log keeps the range story about the QUANTIZED path). One
    jitted step, params/opt-state/amax-history donated."""

    def __init__(self, sizes, optimizer, seed: int = 0):
        self.sizes = list(sizes)
        self.opt = optimizer
        self.params = init_fp8_mlp(sizes, seed)
        self.opt_state = optimizer.init(self.params)
        n_layers = len(sizes) - 1
        # seed the history at 1.0 (scale ~ 1/448): conservative for
        # O(1) activations, and never zero — the scale divide must be
        # provably nonzero
        self.amax_hist = jnp.ones((n_layers, AMAX_HISTORY), jnp.float32)
        self.last_health = None
        self._step_fn = jax.jit(self._step, donate_argnums=(0, 1, 2))
        self._loss_fn = jax.jit(self._loss)

    # ------------------------------------------------------- the step

    def _forward(self, params, scales, x):
        """Returns (prediction, per-layer input absmaxes). The absmax
        is measured on the f32 input of each quantized matmul — the
        stat the delayed scale of FUTURE steps is built from."""
        h = x
        amaxes = []
        n = len(params["layers"])
        for i, layer in enumerate(params["layers"]):
            amaxes.append(jnp.max(jnp.abs(h)))
            h = fp8_dense(h, layer["W"], scales[i]) + layer["b"]
            if i < n - 1:
                h = jax.nn.relu(h)
        return h, jnp.stack(amaxes)

    def _loss(self, params, amax_hist, x, y):
        scales = self._scales(amax_hist)
        pred, _ = self._forward(params, scales, x)
        return jnp.mean(jnp.square(pred - y))

    @staticmethod
    def _scales(amax_hist):
        """Delayed per-tensor activation scales: window max over the
        amax history, floored away from zero."""
        return jnp.maximum(jnp.max(amax_hist, axis=1) / E4M3_MAX, 1e-12)

    def _step(self, params, opt_state, amax_hist, x, y):
        scales = self._scales(amax_hist)

        def loss_fn(p):
            pred, amaxes = self._forward(p, scales, x)
            return jnp.mean(jnp.square(pred - y)), amaxes

        (loss, amaxes), grads = jax.value_and_grad(
            loss_fn, has_aux=True)(params)
        new_params, new_opt = self.opt.step(params, grads, opt_state)
        # roll the window: slot 0 is this step's measurement
        new_hist = jnp.roll(amax_hist, 1, axis=1).at[:, 0].set(amaxes)
        pack = grad_health(params, grads)
        pack = update_health(pack, params, new_params)
        pack["fp8_amax"] = amaxes
        pack["fp8_scale"] = scales
        return new_params, new_opt, new_hist, loss, pack

    # ---------------------------------------------------- public API

    def train_batch(self, x, y) -> float:
        (self.params, self.opt_state, self.amax_hist, loss,
         pack) = self._step_fn(self.params, self.opt_state,
                               self.amax_hist, x, y)
        note_step(self, pack)
        return float(loss)

    def eval_loss(self, x, y) -> float:
        return float(self._loss_fn(self.params, self.amax_hist, x, y))

    def health_snapshot(self) -> dict | None:
        from shallowspeed_tpu.telemetry.health import engine_snapshot
        return engine_snapshot(self)
