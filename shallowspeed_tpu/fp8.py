"""fp8-e4m3 forward-matmul training — ROADMAP item 5's first rung.

A deliberately minimal trainer whose every numerics decision is the
one the static prover certifies (`analysis --target fp8_train`):

- **forward** matmuls run in fp8-e4m3 via `ops.matmul.fp8_dense` —
  activations quantized with a DELAYED per-tensor scale, weights with a
  just-in-time per-out-channel scale, dequant fused onto the f32
  accumulator (the `dequant_matmul` discipline, extended to training).
- **delayed scaling** (the Transformer-Engine recipe): this step's
  activation absmaxes only feed the NEXT steps' scales, through a
  rolling per-layer amax history carried in the step like optimizer
  state. The history rides the health pack (`fp8_amax` / `fp8_scale`)
  so a drifting scale is visible at every log point, next to grad
  norms.
- **backward** is a hand straight-through VJP: gradients stay f32
  end-to-end (autodiff through the quantization casts would re-round
  cotangents through e4m3 — the exact `fp8-double-rounding` bug
  class), and parameters/optimizer state are f32 master copies.

Round 18 adds the RUNTIME half of the rollout gate on top of the
static certificate:

- the **numerics pack**: per-layer overflow/underflow fractions at
  every activation quantize (`ops.matmul.fp8_clamp_stats`) join
  `fp8_amax`/`fp8_scale` in the health pack — computed inside the same
  compiled step (zero new executables, zero recompiles; pinned by
  tests/test_numerics.py), reduced host-side by
  `telemetry.numerics.NumericsMonitor`.
- **shadow parity** (`shadow_parity(x, y)`): a frozen master-precision
  oracle step on the same batch — no state update — reporting the
  loss rel-err and worst-leaf gradient relmax of the quantized step
  against f32. The drivers sample it every N steps (ledger-excluded as
  `shadow_parity`) and feed the monitor's parity-drift detector.
- a **bf16 fallback** (`fallback_bf16()`): the guard escalation's
  middle rung — subsequent steps run the master-precision matmuls
  while the amax history keeps rolling (state shapes, pack keys and
  the scale series stay intact), so a run whose scales collapsed keeps
  training inside the oracle's loss envelope instead of aborting.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from shallowspeed_tpu.ops.matmul import (E4M3_MAX, fp8_clamp_stats,
                                         fp8_dense)
from shallowspeed_tpu.telemetry.health import (grad_health, note_step,
                                               update_health)

tree_map = jax.tree_util.tree_map

# rolling absmax window (steps) behind the delayed activation scale
AMAX_HISTORY = 16

# engine compute modes: "fp8" is the quantized path the static prover
# certifies; "bf16" is the master-precision fallback the numerics
# guard escalates to (the matmuls run un-quantized; everything else —
# amax bookkeeping, pack keys, state shapes — is unchanged)
PRECISION_MODES = ("fp8", "bf16")


def init_fp8_mlp(sizes, seed: int = 0) -> dict:
    """f32 master params for a dense ReLU MLP: He-scaled weights, zero
    biases — `sizes` is [d_in, hidden..., d_out]."""
    rng = np.random.default_rng(seed)
    layers = []
    for fan_in, fan_out in zip(sizes[:-1], sizes[1:]):
        w = rng.standard_normal((fan_in, fan_out)) * np.sqrt(2.0 / fan_in)
        layers.append({"W": jnp.asarray(w, jnp.float32),
                       "b": jnp.zeros((fan_out,), jnp.float32)})
    return {"layers": layers}


class Fp8TrainEngine:
    """Single-device fp8 forward-matmul trainer (MSE regression head —
    no exp/log keeps the range story about the QUANTIZED path). One
    jitted step, params/opt-state/amax-history donated."""

    def __init__(self, sizes, optimizer, seed: int = 0,
                 precision: str = "fp8"):
        if precision not in PRECISION_MODES:
            raise ValueError(
                f"unsupported precision={precision!r}; expected one of "
                f"{PRECISION_MODES} (fp8 = quantized forward matmuls, "
                f"bf16 = the master-precision fallback path)")
        if len(sizes) < 2 or any(int(s) < 1 for s in sizes):
            raise ValueError(
                f"sizes must be [d_in, hidden..., d_out] with positive "
                f"dims, got {list(sizes)!r}")
        self.sizes = list(sizes)
        self.opt = optimizer
        self.precision = precision
        self.params = init_fp8_mlp(sizes, seed)
        self.opt_state = optimizer.init(self.params)
        n_layers = len(sizes) - 1
        # seed the history at 1.0 (scale ~ 1/448): conservative for
        # O(1) activations, and never zero — the scale divide must be
        # provably nonzero
        self.amax_hist = jnp.ones((n_layers, AMAX_HISTORY), jnp.float32)
        self.last_health = None
        self._step_fn = jax.jit(self._step, donate_argnums=(0, 1, 2))
        self._loss_fn = jax.jit(self._loss)
        # the fallback step and the shadow-parity oracle are compiled
        # LAZILY on first use: neither may add an executable to a run
        # that never leaves the fp8 path (the zero-new-executables pin)
        self._fallback_fn = None
        self._parity_fn = None

    # ------------------------------------------------------- the step

    def _forward(self, params, scales, x):
        """Returns (prediction, per-layer input absmaxes, per-layer
        (overflow, underflow) clamp fractions). The absmax is measured
        on the f32 input of each quantized matmul — the stat the
        delayed scale of FUTURE steps is built from; the clamp stats
        describe what the clip did to THIS step's operands."""
        h = x
        amaxes, overflows, underflows = [], [], []
        n = len(params["layers"])
        for i, layer in enumerate(params["layers"]):
            amaxes.append(jnp.max(jnp.abs(h)))
            over, under = fp8_clamp_stats(h, scales[i])
            overflows.append(over)
            underflows.append(under)
            h = fp8_dense(h, layer["W"], scales[i]) + layer["b"]
            if i < n - 1:
                h = jax.nn.relu(h)
        return (h, jnp.stack(amaxes), jnp.stack(overflows),
                jnp.stack(underflows))

    def _oracle_forward(self, params, x):
        """The frozen master-precision forward: same architecture, f32
        matmuls, no quantize — the parity oracle and the bf16-fallback
        step's compute path. Absmaxes are still measured so the amax
        history keeps rolling under fallback."""
        h = x
        amaxes = []
        n = len(params["layers"])
        for i, layer in enumerate(params["layers"]):
            amaxes.append(jnp.max(jnp.abs(h)))
            h = jnp.dot(h, layer["W"],
                        preferred_element_type=jnp.float32) + layer["b"]
            if i < n - 1:
                h = jax.nn.relu(h)
        return h, jnp.stack(amaxes)

    def _loss(self, params, amax_hist, x, y):
        scales = self._scales(amax_hist)
        pred, _, _, _ = self._forward(params, scales, x)
        return jnp.mean(jnp.square(pred - y))

    @staticmethod
    def _scales(amax_hist):
        """Delayed per-tensor activation scales: window max over the
        amax history, floored away from zero."""
        return jnp.maximum(jnp.max(amax_hist, axis=1) / E4M3_MAX, 1e-12)

    def _step(self, params, opt_state, amax_hist, x, y):
        scales = self._scales(amax_hist)

        def loss_fn(p):
            pred, amaxes, over, under = self._forward(p, scales, x)
            return jnp.mean(jnp.square(pred - y)), (amaxes, over, under)

        ((loss, (amaxes, over, under)), grads) = jax.value_and_grad(
            loss_fn, has_aux=True)(params)
        new_params, new_opt = self.opt.step(params, grads, opt_state)
        # roll the window: slot 0 is this step's measurement
        new_hist = jnp.roll(amax_hist, 1, axis=1).at[:, 0].set(amaxes)
        pack = grad_health(params, grads)
        pack = update_health(pack, params, new_params)
        pack["fp8_amax"] = amaxes
        pack["fp8_scale"] = scales
        pack["fp8_overflow"] = over
        pack["fp8_underflow"] = under
        return new_params, new_opt, new_hist, loss, pack

    def _step_bf16(self, params, opt_state, amax_hist, x, y):
        """The fallback step: master-precision matmuls, IDENTICAL state
        and pack structure. Clamp fractions are exact zeros (nothing is
        quantized) and the amax history keeps rolling, so a later
        return to fp8 starts from fresh scales, not stale ones."""
        scales = self._scales(amax_hist)

        def loss_fn(p):
            pred, amaxes = self._oracle_forward(p, x)
            return jnp.mean(jnp.square(pred - y)), amaxes

        (loss, amaxes), grads = jax.value_and_grad(
            loss_fn, has_aux=True)(params)
        new_params, new_opt = self.opt.step(params, grads, opt_state)
        new_hist = jnp.roll(amax_hist, 1, axis=1).at[:, 0].set(amaxes)
        pack = grad_health(params, grads)
        pack = update_health(pack, params, new_params)
        pack["fp8_amax"] = amaxes
        pack["fp8_scale"] = scales
        zeros = jnp.zeros_like(scales)
        pack["fp8_overflow"] = zeros
        pack["fp8_underflow"] = zeros
        return new_params, new_opt, new_hist, loss, pack

    def _parity(self, params, amax_hist, x, y):
        """Shadow-parity probe: the quantized loss/grads and the frozen
        f32-oracle loss/grads on the SAME batch, no state update.
        Returns (loss_rel_err, worst-leaf grad relmax) — the runtime
        loss-parity gate's two scalars."""
        scales = self._scales(amax_hist)

        def q_loss(p):
            pred, _, _, _ = self._forward(p, scales, x)
            return jnp.mean(jnp.square(pred - y))

        def o_loss(p):
            pred, _ = self._oracle_forward(p, x)
            return jnp.mean(jnp.square(pred - y))

        ql, qg = jax.value_and_grad(q_loss)(params)
        ol, og = jax.value_and_grad(o_loss)(params)
        loss_rel = jnp.abs(ql - ol) / jnp.maximum(jnp.abs(ol), 1e-12)

        def leaf_rel(a, b):
            return jnp.max(jnp.abs(a - b)) / jnp.maximum(
                jnp.max(jnp.abs(b)), 1e-12)

        rels = tree_map(leaf_rel, qg, og)
        grad_relmax = jnp.max(jnp.stack(
            jax.tree_util.tree_leaves(rels)))
        return loss_rel, grad_relmax

    # ---------------------------------------------------- public API

    def train_batch(self, x, y) -> float:
        if self.precision == "bf16":
            if self._fallback_fn is None:
                self._fallback_fn = jax.jit(self._step_bf16,
                                            donate_argnums=(0, 1, 2))
            step_fn = self._fallback_fn
        else:
            step_fn = self._step_fn
        (self.params, self.opt_state, self.amax_hist, loss,
         pack) = step_fn(self.params, self.opt_state,
                         self.amax_hist, x, y)
        note_step(self, pack)
        return float(loss)

    def fallback_bf16(self) -> None:
        """Switch subsequent steps to the master-precision fallback —
        the guard escalation's middle rung. Idempotent."""
        self.precision = "bf16"

    def shadow_parity(self, x, y) -> dict:
        """One ledger-excluded oracle comparison on `(x, y)` — the
        caller stamps the seconds as `shadow_parity`. Returns host
        floats ready for `NumericsMonitor.note_parity`."""
        if self._parity_fn is None:
            self._parity_fn = jax.jit(self._parity)
        loss_rel, grad_relmax = self._parity_fn(
            self.params, self.amax_hist, x, y)
        return {"parity_loss_rel": float(loss_rel),
                "parity_grad_relmax": float(grad_relmax)}

    def eval_loss(self, x, y) -> float:
        return float(self._loss_fn(self.params, self.amax_hist, x, y))

    def health_snapshot(self) -> dict | None:
        from shallowspeed_tpu.telemetry.health import engine_snapshot
        return engine_snapshot(self)
