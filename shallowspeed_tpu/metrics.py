"""Metrics / observability — structured training logs.

The reference's observability is bare `print` (SURVEY §5: epoch/time/accuracy
lines, `/root/reference/train.py:135-137,150-152`). This keeps that console
surface (via `utils.rprint`) and adds a structured JSONL sink so runs are
machine-comparable: one line per epoch with wall-clock, accuracy, and
throughput.
"""

from __future__ import annotations

import json
import time
from pathlib import Path


class MetricsLogger:
    """Append-only JSONL metrics writer; no-op when path is falsy."""

    def __init__(self, path=None, **run_info):
        self.path = Path(path) if path else None
        self._t0 = time.time()
        if self.path:
            self.path.parent.mkdir(parents=True, exist_ok=True)
            self.log(event="run_start", **run_info)

    def log(self, **fields) -> None:
        if not self.path:
            return
        fields.setdefault("t", round(time.time() - self._t0, 3))
        with self.path.open("a") as f:
            f.write(json.dumps(fields) + "\n")

    def epoch(self, epoch: int, accuracy_start: float, samples: int,
              epoch_seconds: float) -> None:
        """One record per training epoch. `accuracy_start` is the validation
        accuracy measured BEFORE this epoch's updates (the reference's print
        semantics, `train.py:135-137`); the trained result lands in the
        `final` record."""
        sps = samples / epoch_seconds if epoch_seconds > 0 else 0.0
        self.log(event="epoch", epoch=epoch,
                 accuracy_start=round(accuracy_start, 6),
                 epoch_seconds=round(epoch_seconds, 4),
                 samples_per_sec=round(sps, 1))

    def final(self, accuracy: float, total_seconds: float) -> None:
        """Post-training validation accuracy — the run's headline result."""
        self.log(event="final", accuracy=round(accuracy, 6),
                 total_seconds=round(total_seconds, 3))


class StepRates:
    """Per-window AND cumulative training throughput, with pauses
    (validation, checkpoint saves) excluded from both.

    Round-4 lesson: logging only the cumulative average buried the
    sustained rate — the endurance run's step lines read 0.27 MFU while
    the true steady-state (recoverable only by offline differencing of
    the cumulative counters) was 0.63, because early compile time never
    leaves a cumulative denominator. The WINDOW rate — delta-tokens over
    delta-wall-time between log points, pauses excluded — is the number
    a sustained-MFU claim reads directly off any metrics.jsonl line; the
    cumulative stays alongside as the whole-run summary.
    """

    def __init__(self, tokens_per_step: float, clock=time.time):
        self.tokens_per_step = float(tokens_per_step)
        self._clock = clock
        self._t0 = clock()
        self._pause = 0.0         # total excluded seconds since start
        self._win_t = self._t0    # wall clock at the last log point
        self._win_pause = 0.0     # excluded seconds at the last log point
        self._steps = 0           # steps accounted across all windows

    def pause(self, seconds: float) -> None:
        """Exclude `seconds` of non-training wall time (val eval, ckpt
        save — including an async save's caller-thread snapshot fetch,
        which stalls the step loop for minutes on big models)."""
        self._pause += float(seconds)

    def log_point(self, steps_since_last: int) -> dict:
        """Close the current window (`steps_since_last` training steps
        since the previous log point) and return both rates."""
        now = self._clock()
        self._steps += int(steps_since_last)
        win_secs = max(now - self._win_t
                       - (self._pause - self._win_pause), 1e-9)
        cum_secs = max(now - self._t0 - self._pause, 1e-9)
        win = self.tokens_per_step * steps_since_last / win_secs
        cum = self.tokens_per_step * self._steps / cum_secs
        self._win_t, self._win_pause = now, self._pause
        return {"tokens_per_sec": win, "tokens_per_sec_cum": cum}
