"""Metrics / observability — structured training logs.

The reference's observability is bare `print` (SURVEY §5: epoch/time/accuracy
lines, `/root/reference/train.py:135-137,150-152`). This keeps that console
surface (via `utils.rprint`) and adds a structured JSONL sink so runs are
machine-comparable: one line per epoch with wall-clock, accuracy, and
throughput.
"""

from __future__ import annotations

import json
import time
from pathlib import Path


class MetricsLogger:
    """Append-only JSONL metrics writer; no-op when path is falsy."""

    def __init__(self, path=None, **run_info):
        self.path = Path(path) if path else None
        self._t0 = time.time()
        if self.path:
            self.path.parent.mkdir(parents=True, exist_ok=True)
            self.log(event="run_start", **run_info)

    def log(self, **fields) -> None:
        if not self.path:
            return
        fields.setdefault("t", round(time.time() - self._t0, 3))
        with self.path.open("a") as f:
            f.write(json.dumps(fields) + "\n")

    def epoch(self, epoch: int, accuracy_start: float, samples: int,
              epoch_seconds: float) -> None:
        """One record per training epoch. `accuracy_start` is the validation
        accuracy measured BEFORE this epoch's updates (the reference's print
        semantics, `train.py:135-137`); the trained result lands in the
        `final` record."""
        sps = samples / epoch_seconds if epoch_seconds > 0 else 0.0
        self.log(event="epoch", epoch=epoch,
                 accuracy_start=round(accuracy_start, 6),
                 epoch_seconds=round(epoch_seconds, 4),
                 samples_per_sec=round(sps, 1))

    def final(self, accuracy: float, total_seconds: float) -> None:
        """Post-training validation accuracy — the run's headline result."""
        self.log(event="final", accuracy=round(accuracy, 6),
                 total_seconds=round(total_seconds, 3))
