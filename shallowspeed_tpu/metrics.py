"""Metrics / observability — structured training logs.

The reference's observability is bare `print` (SURVEY §5: epoch/time/accuracy
lines, `/root/reference/train.py:135-137,150-152`). This keeps that console
surface (via `utils.rprint`) and adds a structured JSONL sink so runs are
machine-comparable: one line per epoch with wall-clock, accuracy, and
throughput. With a `telemetry.RunTelemetry` attached, `StepRates` lines
additionally carry the runtime telemetry fields (live-vs-static HBM,
per-axis collective bytes + implied GB/s, recompile counter, pipeline
bubble fractions) — schema in `telemetry/schema.py`, which also gates
committed `docs_runs/*.jsonl` artifacts at pre-commit time.
"""

from __future__ import annotations

import json
import os
import time
from pathlib import Path


class MetricsLogger:
    """Append-only JSONL metrics writer; no-op when path is falsy.

    With a `telemetry.monitor.Monitor` attached (the drivers set
    `.monitor` when any of --monitor-port / --slo / --flight-recorder
    is on), every logged line is ALSO fed to `Monitor.note_line` —
    the live plane ingests exactly the records the file gets, so the
    /status.json view and the offline reducers read one stream. The
    monitor feed runs even when `path` is falsy (an in-process engine
    can be monitored without a log file)."""

    def __init__(self, path=None, monitor=None, **run_info):
        self.path = Path(path) if path else None
        self.monitor = monitor
        self._t0 = time.time()
        # one persistent append handle, flushed per line (round 16):
        # re-opening the file per log call cost ~100 us per line,
        # which the serving engine's lifecycle stream (several lines
        # per request) turned into a measurable tok/s tax on small
        # models; a flushed append keeps the same durability contract
        # (tailers and supervisors see every completed line, other
        # processes may still append to the same file — O_APPEND)
        self._fh = None
        if self.path:
            from shallowspeed_tpu.telemetry.schema import SCHEMA_VERSION

            self.path.parent.mkdir(parents=True, exist_ok=True)
            self._fh = self.path.open("a")
            self.log(event="run_start", schema_version=SCHEMA_VERSION,
                     **run_info)

    def log(self, **fields) -> None:
        if not self.path and self.monitor is None:
            return
        now = time.time()
        fields.setdefault("t", round(now - self._t0, 3))
        # schema v4: absolute wall stamp on every line, so the goodput
        # reducer can account wall clock ACROSS supervisor restarts
        # (each process's `t` restarts at its own run_start)
        fields.setdefault("wall", round(now, 3))
        # schema v11: the monotonic half of the (wall, monotonic) clock
        # pair — steady within a process even when wall jumps (NTP
        # slew, clock step), so the cross-process trace stitcher
        # (telemetry/tracing.py) can fit one offset per process stanza
        # against the router's dispatch/ack pairs and place every
        # replica's events on a single skew-corrected timeline
        fields.setdefault("mono", round(time.monotonic(), 6))
        if self.path:
            if self._fh is None or self._fh.closed:
                self._fh = self.path.open("a")
            else:
                # external-rotation tolerance (the contract the
                # per-line reopen this handle replaced provided): if
                # the path no longer resolves to the handle's inode
                # (logrotate/operator mv or unlink), reopen by path so
                # later lines land where tailers look — an os.stat per
                # line is ~100x cheaper than the reopen was
                try:
                    st = os.stat(self.path)
                    fst = os.fstat(self._fh.fileno())
                    same = (st.st_ino, st.st_dev) == (fst.st_ino,
                                                      fst.st_dev)
                except OSError:
                    same = False
                if not same:
                    self._fh.close()
                    self._fh = self.path.open("a")
            self._fh.write(json.dumps(fields) + "\n")
            self._fh.flush()
        if self.monitor is not None:
            self.monitor.note_line(fields)

    def close(self) -> None:
        if self._fh is not None and not self._fh.closed:
            self._fh.close()

    def __del__(self):  # best effort — flush() above did the real work
        try:
            self.close()
        except Exception:
            pass

    def epoch(self, epoch: int, accuracy_start: float, samples: int,
              epoch_seconds: float) -> None:
        """One record per training epoch. `accuracy_start` is the validation
        accuracy measured BEFORE this epoch's updates (the reference's print
        semantics, `train.py:135-137`); the trained result lands in the
        `final` record."""
        sps = samples / epoch_seconds if epoch_seconds > 0 else 0.0
        self.log(event="epoch", epoch=epoch,
                 accuracy_start=round(accuracy_start, 6),
                 epoch_seconds=round(epoch_seconds, 4),
                 samples_per_sec=round(sps, 1))

    def final(self, accuracy: float, total_seconds: float) -> None:
        """Post-training validation accuracy — the run's headline result."""
        self.log(event="final", accuracy=round(accuracy, 6),
                 total_seconds=round(total_seconds, 3))


class StepRates:
    """Per-window AND cumulative training throughput, with pauses
    (validation, checkpoint saves) excluded from both.

    Round-4 lesson: logging only the cumulative average buried the
    sustained rate — the endurance run's step lines read 0.27 MFU while
    the true steady-state (recoverable only by offline differencing of
    the cumulative counters) was 0.63, because early compile time never
    leaves a cumulative denominator. The WINDOW rate — delta-tokens over
    delta-wall-time between log points, pauses excluded — is the number
    a sustained-MFU claim reads directly off any metrics.jsonl line; the
    cumulative stays alongside as the whole-run summary.
    """

    def __init__(self, tokens_per_step: float, clock=time.time,
                 telemetry=None, health=None, ledger=None,
                 monitor=None, numerics=None):
        self.tokens_per_step = float(tokens_per_step)
        self._clock = clock
        self._t0 = clock()
        self._pause = 0.0         # total excluded seconds since start
        self._win_t = self._t0    # wall clock at the last log point
        self._win_pause = 0.0     # excluded seconds at the last log point
        self._steps = 0           # steps accounted across all windows
        # optional telemetry.RunTelemetry: when set, every log_point
        # line additionally carries the run's telemetry fields (HBM
        # live/static, per-axis collective bytes + implied GB/s over
        # the closed window, recompile counter, bubble fractions)
        self.telemetry = telemetry
        # optional telemetry.health.HealthMonitor: when set, every
        # log_point line additionally carries the training-health
        # fields (grad/param norms, update ratio, nonfinite counter,
        # skipped-step counter, anomaly verdicts)
        self.health = health
        # optional telemetry.numerics.NumericsMonitor: when set, every
        # log_point line additionally carries the fp8 numerics fields
        # (clamp fractions, scale/amax extrema, drift/oscillation
        # scores, shadow-parity rel-errs, numerics verdicts — the
        # schema-v13 num_* dialect)
        self.numerics = numerics
        # optional telemetry.goodput.GoodputLedger: every `pause` is
        # ALSO stamped as a ledger event of its kind, so the
        # throughput windows and the run-level goodput ledger can
        # never disagree (window-sum + excluded-ledger-seconds ==
        # wall clock by construction; pinned in tests/test_goodput.py),
        # and recompile / guarded-skip DELTAS between log points land
        # as in-window ledger counts
        self.ledger = ledger
        self._led_prev = {"recompiles": 0, "health_skipped_total": 0}
        # optional telemetry.monitor.Monitor: every closed window
        # feeds the live streaming sketches with the EXACT
        # pause-excluded per-step time and window tok/s (the tailer's
        # step-line derivation cannot exclude pauses; this path can —
        # the monitor's derive_steps stays False when this is wired)
        self.monitor = monitor

    def pause(self, seconds: float, kind: str = "pause") -> None:
        """Exclude `seconds` of non-training wall time (val eval, ckpt
        save — including an async save's caller-thread snapshot fetch,
        which stalls the step loop for minutes on big models). `kind`
        names the goodput-ledger bucket the excluded time lands in
        (telemetry/goodput.EXCLUDED_KINDS)."""
        self._pause += float(seconds)
        # sub-0.1ms pauses (e.g. a telemetry call that hit its cache)
        # would write one near-empty ledger line per log point
        if self.ledger is not None and float(seconds) > 1e-4:
            self.ledger.note(kind, seconds=float(seconds))

    def log_point(self, steps_since_last: int) -> dict:
        """Close the current window (`steps_since_last` training steps
        since the previous log point) and return both rates (plus the
        telemetry fields when a RunTelemetry is attached)."""
        now = self._clock()
        self._steps += int(steps_since_last)
        win_secs = max(now - self._win_t
                       - (self._pause - self._win_pause), 1e-9)
        cum_secs = max(now - self._t0 - self._pause, 1e-9)
        win = self.tokens_per_step * steps_since_last / win_secs
        cum = self.tokens_per_step * self._steps / cum_secs
        self._win_t, self._win_pause = now, self._pause
        out = {"tokens_per_sec": win, "tokens_per_sec_cum": cum}
        if self.monitor is not None and steps_since_last > 0:
            # the window's mean per-step time, weighted by its step
            # count — the sketch sees every step at the window average
            self.monitor.observe(
                "step_ms", win_secs * 1e3 / steps_since_last,
                count=int(steps_since_last))
            self.monitor.observe("tok_s", win)
        if self.health is not None:
            out.update(self.health.step_fields())
        if self.numerics is not None:
            out.update(self.numerics.step_fields())
        if self.telemetry is not None:
            out.update(self.telemetry.step_fields(
                window_secs=win_secs,
                steps_in_window=int(steps_since_last)))
            # telemetry's own cost (the one-time static jaxpr trace can
            # be seconds on a big step) must not depress the NEXT
            # window's rate — book it as excluded pause time
            self.pause(self._clock() - now, kind="telemetry")
        if self.ledger is not None:
            # in-window losses: recompiles and guarded skipped steps
            # advance as cumulative counters on the step line — stamp
            # the DELTAS so the reducer can price them
            for field, kind in (("recompiles", "recompiles"),
                                ("health_skipped_total",
                                 "skipped_steps")):
                cur = out.get(field)
                if isinstance(cur, int):
                    delta = cur - self._led_prev[field]
                    if delta > 0:
                        self.ledger.note(kind, count=delta)
                    self._led_prev[field] = cur
        return out
