"""Continuous-batching decode server over the paged KV cache.

`generate()` serves ONE batch synchronously: every row prefills
together, decodes together, and finishes together — concurrent
requests of different lengths either recompile per shape or block
head-of-line behind the longest. This engine serves a STREAM:

- **Fixed-capacity decode slots.** One compiled decode tick advances
  every running request by one token. The tick's row count is pinned
  to `max_slots` and its gathered block-table width is bucketed
  GEOMETRICALLY in blocks, so requests join and leave the running
  batch between ticks with NO recompiles after warmup — one executable
  per (width bucket), pinned like `test_vm_executables_compile_exactly
  _once`. Empty slots still execute (their cache writes are steered to
  the reserved scratch block) — occupancy is DATA, not shape.
- **Chunked prefill.** Prompts prefill `prefill_chunk` tokens per
  engine step, interleaved with decode ticks — an 8k prompt admitted
  mid-run delays in-flight decodes by at most one chunk per tick
  instead of one full prefill. Chunks are padded to the fixed chunk
  length with the true length traced (the `prompt_bucket_len` idea at
  chunk granularity), pad positions write to scratch.
- **Admission + preemption.** A request is admitted when a slot and
  its prompt's blocks are free; a decode append that finds the pool
  empty EVICTS the newest-admitted running request (its blocks free
  immediately, it re-queues at the front and later re-prefills its
  prompt + already-generated tokens, continuing its stream where it
  left off). Evicting the NEWEST — the request that has waited least
  — is what makes the policy livelock-free: the oldest running
  request always progresses, and `submit` rejects requests that
  could never fit alone, so the allocator cannot deadlock.
- **Per-request SLO telemetry.** Every completion stamps a schema-v6
  `"request"` event (ttft_ms, tpot_ms, queue depth, preemptions,
  tokens in/out) into the run's metrics JSONL; periodic `"generate"`
  lines carry tick throughput and the live-blocks HBM sweep
  (`cache.paged_read_bytes_per_tick` — the serving generalization of
  `decode_read_bytes_per_token`).
- **Per-request lifecycle tracing** (round 13). Every request carries
  a phase timeline (submit -> queued -> admitted -> prefill chunk k ->
  decoding -> preempted -> requeued -> finished): each transition
  stamps a schema-v8 `"lifecycle"` event (with the ms spent in the
  previous phase — `report.request_timeline` reconstructs the whole
  accounting) and, under a live tracer, closes the previous phase as
  a span on the request's own NAMED Chrome-trace track, cross-linked
  to the engine tick counter. Fleet views resolve a burning SLO to
  "which request, which phase, which replica" through this.

- **Fast decode path** (round 14, ROADMAP item 1) — three composable
  levers, each individually gated:
  - *Quantized weight storage* (`weight_quant="int8"|"fp8"`): the
    params tree is quantized ONCE at init (`T.quantize_weights`) into
    int8/fp8-e4m3 matrices + per-out-channel f32 scales; every dense
    in the tick runs the fused-dequant matmul
    (`ops.matmul.dequant_matmul` — scale on the f32 accumulator,
    never a materialized dequantized copy; proved by the analysis
    `dequant-fusion` rule over this very tick). The params term of
    `paged_read_bytes_per_tick` shrinks to ~0.5x bf16.
  - *Paged flash-decode kernel* (`attn_impl="flash"`): the tick's
    attention runs `ops.flash_attention.paged_flash_decode` — grid
    over the block table via scalar-prefetch index maps, online
    softmax across a row's blocks, int8 KV + scales read natively —
    instead of materializing `gather_table`'s contiguous copy.
    `gather` stays the default AND the reference the kernel is pinned
    against (<= 1e-4).
  - *Speculative decoding* (`spec_k > 0`): a self-drafting n-gram
    prompt-lookup proposer (`_propose`) fills FREE rows of the
    fixed-capacity tick with up to K draft tokens per decoding
    request at consecutive positions; the same compiled tick verifies
    them all in one pass (each row's mask admits the rows before it —
    the in-tick writes land before any gather). Acceptance is the
    deterministic accept/resample rule specialized to a point-mass
    (deterministic) draft distribution under a counter-based sampler:
    every emitted token IS the oracle draw `sample(fold_in(
    PRNGKey(seed), i), logits_i)` at its own index — row j's logits
    are the true next-token logits whenever all earlier drafts
    matched their oracle draws — so the output stream is
    TOKEN-IDENTICAL to solo `generate()` at every temperature, not
    merely distribution-equal. Rejected rows' cache writes sit beyond
    the request's advanced position and are overwritten before any
    mask can admit them (the prefill-padding argument). Zero new
    executables: drafts are data in rows that already executed empty.

Stream parity: sampling uses the SAME per-request key schedule as
`generate()` — token i of a request with sampling seed s draws from
`fold_in(PRNGKey(s), i)` — and the paged attention shares
`kv_cache.masked_attention` with the contiguous path, so each
request's stream reproduces its solo `generate()` stream
token-for-token (pinned in tests/test_serving.py; see `generate`'s
stream-stability contract for the ~1e-6 numerics caveat).
"""

from __future__ import annotations

import time
import weakref
from collections import deque
from functools import partial

import jax
import jax.numpy as jnp
import numpy as np

from shallowspeed_tpu import chaos
from shallowspeed_tpu.models import generate as G
from shallowspeed_tpu.ops.flash_attention import paged_flash_decode
from shallowspeed_tpu.telemetry.profiler import tag as phase_tag
from shallowspeed_tpu.telemetry.trace import tracer
from shallowspeed_tpu.telemetry.tracing import new_span_id, new_trace_id
from shallowspeed_tpu.models import transformer as T
from shallowspeed_tpu.models.kv_cache import masked_attention
from shallowspeed_tpu.serving.cache import (SCRATCH_BLOCK, BlockAllocator,
                                            OutOfBlocks, PrefixIndex,
                                            blocks_for, gather_table,
                                            init_block_pool,
                                            paged_read_bytes_per_tick,
                                            param_read_bytes, write_rows)


# finished-request timelines the engine retains in memory for
# in-process consumers (bench phase accounting, tests); older entries
# evict FIFO — the metrics JSONL carries the complete lifecycle stream
TIMELINE_CAP = 1024


class EngineDraining(RuntimeError):
    """`submit()` after `drain()` began.

    A draining replica finishes the work it already accepted and
    admits nothing new — the typed rejection (instead of the old
    implicit behavior: queued-forever under load shedding, silent
    acceptance after a drain request) is what lets a fleet router
    re-route the request instead of wedging it on a replica that is
    about to deregister. `pending` carries the in-flight count so the
    caller can size its retry-after."""

    def __init__(self, pending: int):
        super().__init__(
            f"engine is draining ({pending} accepted request(s) still "
            f"in flight); submit to another replica")
        self.pending = int(pending)


def table_width(n_blocks: int, base: int) -> int:
    """Geometric block-table width bucket (base, 2*base, 4*base, ...):
    the compile key for the gathered reads. Linear bucketing would
    compile O(prompt/bucket) executables as a long prompt's table
    grows; geometric pins the executable count at O(log) — the
    serving analog of `prompt_bucket_len`."""
    w = max(1, int(base))
    n = max(1, int(n_blocks))
    while w < n:
        w *= 2
    return w


def _rope_rows(x, pos, theta: float):
    """`T.rope_rotate` with a PER-ROW position: x (S, 1, H, D), pos
    (S,) — each slot decodes at its own global position. Swapping the
    row axis into rope_rotate's sequence axis reuses the ONE rotary
    implementation (same half-split math and f32 phases), so a row's
    values equal the contiguous path's at the same position by
    construction."""
    return jnp.swapaxes(
        T.rope_rotate(jnp.swapaxes(x, 0, 1), pos, theta), 0, 1)


def _sample_rows(logits, temp, seeds, idx, top_k: int, top_p: float):
    """Row-wise `generate._sample`: per-row temperature and sampling
    key (`fold_in(PRNGKey(seed), idx)` — `idx` is the request's token
    index, so a slot's draws equal its solo `generate()` draws).
    temp == 0 rows take the greedy argmax; top_k/top_p are engine-wide
    statics (lax.top_k needs a static k)."""
    greedy = jnp.argmax(logits, axis=-1).astype(jnp.int32)
    l = G.filter_logits(logits / jnp.maximum(temp, 1e-6)[:, None],
                        top_k, top_p)

    def draw(seed, i, row):
        key = jax.random.fold_in(jax.random.PRNGKey(seed), i)
        return jax.random.categorical(key, row, axis=-1)

    sampled = jax.vmap(draw)(seeds, idx, l).astype(jnp.int32)
    return jnp.where(temp <= 0.0, greedy, sampled)


_sample_jit = jax.jit(_sample_rows, static_argnames=("top_k", "top_p"))


@partial(jax.jit, static_argnames=("cfg", "top_k", "top_p", "attn"),
         donate_argnums=(1,))
def _decode_tick(params, pools, tok, pos, bt, temp, seeds, idx, *,
                 cfg: T.TransformerConfig, top_k: int, top_p: float,
                 attn: str = "gather"):
    """One compiled decode tick over the whole slot batch.

    tok/pos/temp/seeds/idx: (S,) per-slot last token, write position,
    sampling state; bt: (S, W) block tables (W is the bucketed width —
    the ONLY shape that varies across ticks). Each slot writes its
    token's K/V at (bt[pos // bs], pos % bs) and attends over its
    gathered table under the position mask; inactive slots carry
    pos=0 / bt=scratch and their results are ignored host-side.
    Returns (next token per slot, updated pools); pools are DONATED —
    the caches update in place across ticks.

    `attn="flash"` swaps the gather + masked_attention read for the
    fused `paged_flash_decode` kernel (same math, no materialized
    gathered table); "gather" stays the XLA reference the kernel is
    pinned against. Draft rows (speculative decoding) are ordinary
    rows at consecutive positions of a shared table: the pool write
    happens before the read in BOTH paths, so row j's attention sees
    rows i < j of the same tick — the single-pass verify."""
    params = T.cast_params(params, cfg.compute_dtype)
    s_rows = tok.shape[0]
    bs = pools[0]["k"].shape[2]
    w = bt.shape[1]
    quant = "k_s" in pools[0]
    x = params["tok_emb"][tok][:, None, :]                  # (S, 1, d)
    if not cfg.rope:
        x = x + params["pos_emb"][pos][:, None, :]
    if cfg.compute_dtype is not None:
        x = x.astype(cfg.compute_dtype)
    rows = jnp.arange(s_rows)
    blk = bt[rows, pos // bs]
    off = pos % bs
    if attn != "flash":
        span = jnp.arange(w * bs)
        valid = span[None, :] <= pos[:, None]               # (S, W*bs)
        if cfg.attn_window > 0:
            valid = valid & (span[None, :]
                             > pos[:, None] - cfg.attn_window)
        valid = valid[:, None, None, None, :]
    new_pools = []
    for p, pool in zip(params["blocks"], pools):
        h = T._norm(p["ln1"], x, cfg)
        q, k, v = T._qkv(p, h, cfg)
        if cfg.rope:
            q = _rope_rows(q, pos, cfg.rope_theta)
            k = _rope_rows(k, pos, cfg.rope_theta)
        pool = {**pool, **write_rows(pool, k[:, 0], v[:, 0], blk, off,
                                     quant)}
        if attn == "flash":
            a = paged_flash_decode(q[:, 0], pool, bt, pos,
                                   window=cfg.attn_window)
        else:
            a = masked_attention(q, gather_table(pool, bt), valid, cfg)
        x = x + T._dense(p["proj"], a.reshape(s_rows, 1, cfg.d_model))
        h = T._norm(p["ln2"], x, cfg)
        x, _aux = T._ffn(p, x, cfg, h)
        new_pools.append(pool)
    x = T._norm(params["ln_f"], x, cfg)
    logits = T.head_logits(params, x[:, 0], cfg).astype(jnp.float32)
    nxt = _sample_rows(logits, temp, seeds, idx, top_k, top_p)
    return nxt, new_pools


@partial(jax.jit, static_argnames=("cfg",), donate_argnums=(1,))
def _prefill_chunk(params, pools, tokens, pos0, n_tok, bt, cow_src,
                   cow_dst, *, cfg: T.TransformerConfig):
    """One chunk of a request's prefill: tokens (1, C) — C is the
    fixed chunk length, `n_tok` the traced true count (the tail is
    padding, steered to the scratch block exactly like `generate`'s
    bucket padding is overwritten-before-read). Writes the chunk's
    K/V through the block table and attends causally over the table
    (earlier chunks included). Returns (f32 logits at the chunk's last
    true position — consumed only on the final chunk — and the
    updated, donated pools).

    PREFIX-CACHE ALIGNMENT CONTRACT: cache hits are granular to WHOLE
    blocks — `pos0` on a hit is the matched aligned token count, so the
    partial tail (and on a fully-aligned match, the final token of the
    copied tail block) always re-prefills through here; the engine
    never trusts a partially-filled shared block. `cow_src`/`cow_dst`
    are the copy-on-write pair: before any write, every pool leaf
    copies block `cow_src` into block `cow_dst` (one block per layer),
    so a request that diverges inside an otherwise-shared tail block
    writes its OWN copy and the shared block stays bit-unchanged.
    Cache-off and already-diverged calls pass scratch for both — a
    scratch->scratch self-copy that is a no-op by construction (nothing
    reads scratch). Riding the copy inside this one jitted program (as
    data, every call) keeps `executable_counts()` flat: cache hits
    change block-table *data*, never the compiled-program set."""
    params = T.cast_params(params, cfg.compute_dtype)
    c = tokens.shape[1]
    bs = pools[0]["k"].shape[2]
    w = bt.shape[1]
    quant = "k_s" in pools[0]
    pools = [{name: leaf.at[cow_dst].set(leaf[cow_src])
              for name, leaf in pool.items()} for pool in pools]
    pos = pos0 + jnp.arange(c)
    x = G._embed(params, tokens, pos0, cfg)                  # (1, C, d)
    j = jnp.arange(c)
    keep = j < n_tok
    blk = jnp.where(keep, bt[0, jnp.clip(pos // bs, 0, w - 1)],
                    SCRATCH_BLOCK)
    off = jnp.where(keep, pos % bs, 0)
    span = jnp.arange(w * bs)
    valid = span[None, :] <= pos[:, None]                   # (C, W*bs)
    if cfg.attn_window > 0:
        valid = valid & (span[None, :] > pos[:, None] - cfg.attn_window)
    valid = valid[None, None, None, :, :]
    new_pools = []
    for p, pool in zip(params["blocks"], pools):
        h = T._norm(p["ln1"], x, cfg)
        q, k, v = T._qkv(p, h, cfg)
        if cfg.rope:
            q = T.rope_rotate(q, pos, cfg.rope_theta)
            k = T.rope_rotate(k, pos, cfg.rope_theta)
        pool = {**pool, **write_rows(pool, k[0], v[0], blk, off, quant)}
        a = masked_attention(q, gather_table(pool, bt), valid, cfg)
        x = x + T._dense(p["proj"], a.reshape(1, c, cfg.d_model))
        h = T._norm(p["ln2"], x, cfg)
        x, _aux = T._ffn(p, x, cfg, h)
        new_pools.append(pool)
    x = T._norm(params["ln_f"], x, cfg)
    x_last = jax.lax.dynamic_index_in_dim(x, n_tok - 1, 1, False)
    logits = T.head_logits(params, x_last, cfg).astype(jnp.float32)
    return logits, new_pools


class _Req:
    """Host-side request state (never crosses into a trace)."""

    __slots__ = ("rid", "prompt", "max_new", "temp", "seed", "arrival",
                 "generated", "n_preempt", "phase", "slot", "ctx",
                 "table", "written", "admit_seq", "admit_t",
                 "queued_at", "wait_s", "first_tok_t", "last_tok",
                 "timeline", "track", "trace_t0", "n_drafted",
                 "n_accepted", "ctx_ids", "spec_idx",
                 "trace", "span", "parent", "attempt",
                 "hit_blocks", "skipped_tok", "cow")

    def __init__(self, rid, prompt, max_new, temp, seed, arrival):
        self.rid = rid
        self.prompt = prompt
        self.max_new = int(max_new)
        self.temp = float(temp)
        self.seed = int(seed)
        self.arrival = arrival
        self.generated: list[int] = []
        self.n_preempt = 0
        self.phase = "queued"           # queued -> prefill -> decode
        self.slot = None
        self.ctx = prompt               # prompt (+ generated on requeue)
        self.table: list[int] = []
        self.written = 0                # cache positions filled
        self.admit_seq = -1
        self.admit_t = None
        self.queued_at = arrival        # start of the CURRENT queue stint
        self.wait_s = 0.0               # queue time over every stint
        self.first_tok_t = None
        self.last_tok = 0
        # lifecycle tracing (schema v8): the host-side phase timeline,
        # plus this request's named Chrome-trace track
        self.timeline: list[dict] = []
        self.track = None
        self.trace_t0 = None
        # speculative decoding (schema v9): drafted/accepted tallies
        # + the lazily-built n-gram occurrence index (`_spec_state`)
        self.n_drafted = 0
        self.n_accepted = 0
        self.ctx_ids = None
        self.spec_idx = None
        # trace context (schema v11, telemetry/tracing.py): trace id
        # propagated from the fleet router (or minted here for
        # standalone serving), this engine attempt's own span id, the
        # upstream dispatch span, and the 0-based cross-engine
        # dispatch attempt counter
        self.trace = None
        self.span = None
        self.parent = None
        self.attempt = 0
        # prefix caching (schema v14): blocks mapped from the shared
        # index across every admission stint, prefill tokens those
        # mappings skipped, and the pending (src, dst) copy-on-write
        # pair the first prefill chunk after a fully-aligned hit
        # resolves (None otherwise)
        self.hit_blocks = 0
        self.skipped_tok = 0
        self.cow = None


class ServingEngine:
    """Paged-cache continuous-batching decode server (module
    docstring). `submit`/`poll`/`step`/`run` are the programmatic API
    `serve.py` drives; `metrics` (a `metrics.MetricsLogger`) receives
    the schema-v6 `"request"` events and periodic `"generate"` tick
    lines."""

    def __init__(self, params, cfg: T.TransformerConfig, *,
                 n_blocks: int = 64, block_size: int = 16,
                 max_slots: int = 4, prefill_chunk: int = 32,
                 table_bucket: int = 4, kv_quant: str = "",
                 weight_quant: str = "", attn_impl: str = "gather",
                 spec_k: int = 0, spec_ngram: int = 3,
                 top_k: int = 0, top_p: float = 0.0, metrics=None,
                 log_every: int = 0, clock=time.time,
                 lifecycle: bool = True, chaos_plan=None,
                 prefix_cache: bool = False):
        if attn_impl not in ("gather", "flash"):
            raise ValueError(
                f"unsupported attn_impl={attn_impl!r}; expected "
                f"'gather' (the XLA reference) or 'flash' (the paged "
                f"Pallas decode kernel)")
        # quantize ONCE at init (host-side, idempotent): every tick
        # then reads 1-byte weights through the fused-dequant matmul
        self.params = T.quantize_weights(params, weight_quant)
        self.weight_quant = weight_quant
        self.attn_impl = attn_impl
        # speculative decoding: K draft tokens per decoding request per
        # tick, drafted by the n-gram prompt-lookup proposer
        self.spec_k = int(spec_k)
        self.spec_ngram = int(spec_ngram)
        self.cfg = cfg
        self.block_size = int(block_size)
        self.max_slots = int(max_slots)
        self.prefill_chunk = int(prefill_chunk)
        self.table_bucket = int(table_bucket)
        self.kv_quant = kv_quant
        self.top_k = int(top_k)
        self.top_p = float(top_p)
        self.metrics = metrics
        self.log_every = int(log_every)
        self.clock = clock
        # per-request lifecycle tracing (round 13): schema-v8
        # "lifecycle" metrics events + one named Chrome-trace track
        # per request. Costs one host dict per phase transition; only
        # writes when a metrics sink / live tracer is attached.
        self.lifecycle = bool(lifecycle)
        # chaos plan consulted at every engine step (tick-indexed:
        # stall sleeps, kill/nan poison ride the same hooks training
        # uses). None falls back to the process-global plan, so
        # serve.py --chaos and supervisor-exported drills just work;
        # tests pass an explicit plan to fault ONE of N engines.
        self.chaos_plan = chaos_plan
        self.pools = init_block_pool(cfg, n_blocks, block_size, kv_quant)
        # prefix caching (round 19): a content-addressed index over
        # block-aligned prompt chunks. `_admit` probes it, finished
        # requests donate their sealed prefix blocks (refcount-zero
        # indexed blocks park on the allocator's cold LRU list instead
        # of freeing), and the divergence/tail block copies-on-write
        # inside `_prefill_chunk`. Off by default: the strict
        # n_free == n_usable drain invariant holds exactly as before;
        # on, the extended invariant is n_free + n_cold == n_usable at
        # drain (cold = donated, still-matchable cache).
        self.prefix = PrefixIndex(block_size) if prefix_cache else None
        self.alloc = BlockAllocator(n_blocks, index=self.prefix)
        # constant param term at the STORAGE dtypes actually served
        # (int8/fp8 values + f32 scales when weight_quant is on)
        self._p_bytes = param_read_bytes(self.params, cfg)
        self.slots: list[_Req | None] = [None] * self.max_slots
        self.queue: deque[_Req] = deque()
        self.results: dict[str, np.ndarray] = {}
        self.request_records: list[dict] = []
        # finished requests' phase timelines (host dicts, kept for
        # in-process consumers: bench's phase accounting, tests) —
        # the JSONL "lifecycle" stream is the out-of-process surface
        self.timelines: dict[str, list] = {}
        self.counters = {"submitted": 0, "finished": 0, "preempted": 0,
                         "ticks": 0, "prefill_chunks": 0,
                         "shed_toggles": 0, "spec_drafted": 0,
                         "spec_accepted": 0, "prefix_lookups": 0,
                         "prefix_hits": 0, "prefix_skipped_tokens": 0,
                         "oom_events": 0}
        # OOM forensics (round 20, the memory observatory): every
        # RECOVERED OutOfBlocks stamps a typed `oom` ledger line and
        # notifies these listeners with (engine, exc) — serve.py wires
        # the monitor's memory flight dump here (same hook pattern as
        # `on_alert`). Throttled to once per tick: one blocked admit
        # retrying every tick must not flood the ledger.
        self.oom_listeners: list = []
        self._oom_tick = -1
        # ownership registry: the observatory decomposes live HBM by
        # owner. Weakref'd resolvers — registration must not extend
        # this engine's (or its donated pools') lifetime; the LAST
        # engine constructed in a process owns the names (the
        # one-engine-per-process serving deployment; in-process
        # multi-engine tests re-register or ignore).
        from shallowspeed_tpu.telemetry import memory as _memlib

        ref = weakref.ref(self)

        def _own(attr):
            def resolve():
                e = ref()
                return getattr(e, attr) if e is not None else None
            return resolve

        _memlib.register_owner("serving.params", _own("params"))
        _memlib.register_owner("serving.kv_pools", _own("pools"))
        # SLO load shedding (round 12, telemetry/monitor): while
        # `admission_paused`, `_admit` leaves the queue alone — running
        # requests keep every slot/block they hold and drain the
        # latency backlog; queued requests wait (submit() still
        # accepts). `on_alert` is the monitor-facing hook that pauses
        # while ANY SLO rule's critical burn persists (tracked per
        # rule — one rule resolving must not release another rule's
        # shed) — OFF by default: serve.py wires it only under
        # --shed-load, so the alert plane is telemetry-only otherwise.
        self.admission_paused = False
        self._critical_slos: set[str] = set()
        # graceful drain (round 15, fleet router): `drain()` flips this
        # — accepted work (queued AND running) completes, new submits
        # raise the typed EngineDraining. Distinct from the shed pause
        # above: shedding holds the queue and resumes; draining empties
        # the engine for deregistration/scale-down and never resumes.
        self.draining = False
        self._admit_counter = 0
        self._win_tokens = 0            # tokens since the last log line
        self._win_t = clock()
        self._last_touched = 0
        self._win_drafted = 0           # spec-decode window tallies
        self._win_accepted = 0
        self._win_prefix_lookups = 0    # prefix-cache window tallies
        self._win_prefix_hits = 0
        # decode-tick width buckets already executed (and so already
        # compiled): the FIRST tick at a new width re-traces — stamped
        # as a `table_rebucket` ledger event so attribution can book
        # the retrace instead of leaving it unexplained; revisits hit
        # the jit cache and stamp nothing
        self._tick_widths: set[int] = set()
        self._last_width = 0

    # ------------------------------------------------------ public API

    def submit(self, prompt, max_new: int, temperature: float = 0.0,
               seed: int = 0, rid: str | None = None,
               generated=(), trace: str | None = None,
               parent: str | None = None, attempt: int = 0) -> str:
        """Queue one request. Rejects (typed ValueError) requests that
        could never run: prompt + max_new past cfg.max_seq, or a block
        footprint larger than the whole pool (the no-deadlock
        precondition — an admitted request can always finish alone).
        Raises the typed `EngineDraining` after `drain()` began.

        `generated` resumes a half-decoded stream FROM ANOTHER ENGINE:
        the tokens already emitted elsewhere re-prefill with the prompt
        and sampling continues at token index len(generated) — exactly
        the evict-newest continuation mechanism, now crossing a process
        boundary. Because token i of a request always draws from
        `fold_in(PRNGKey(seed), i)`, the continued stream is
        token-identical to the solo `generate()` stream no matter which
        engine emitted the prefix (the fleet router's seeded idempotent
        re-dispatch rides this). `max_new` stays the TOTAL budget; the
        result stream includes the resumed prefix.

        `trace`/`parent`/`attempt` are the schema-v11 trace context
        the router propagates (one trace per fleet request, `parent`
        = the dispatch span, `attempt` = the 0-based cross-engine
        dispatch counter); standalone submissions mint their own
        trace so a lone serve.py's lifecycle stream still stitches.
        This engine mints a fresh span per attempt either way."""
        if self.draining:
            raise EngineDraining(self.pending())
        prompt = np.asarray(prompt, np.int32).reshape(-1)
        tp = prompt.shape[0]
        generated = [int(t) for t in generated]
        if tp < 1 or max_new < 1:
            raise ValueError(f"empty request: prompt {tp} tokens, "
                             f"max_new={max_new}")
        if len(generated) >= max_new:
            raise ValueError(
                f"continuation already carries {len(generated)} of "
                f"max_new={max_new} tokens — nothing left to decode")
        if tp + max_new > self.cfg.max_seq:
            raise ValueError(f"prompt {tp} + max_new {max_new} exceeds "
                             f"max_seq={self.cfg.max_seq}")
        # the final sampled token is never written (sample-after-decode,
        # like generate()), so the request's peak footprint is
        # tp + max_new - 1 cache positions
        need = blocks_for(tp + max_new - 1, self.block_size)
        if need > self.alloc.n_usable:
            raise ValueError(
                f"request needs {need} blocks but the pool holds "
                f"{self.alloc.n_usable} usable — it could never be "
                f"scheduled (raise n_blocks or shrink the request)")
        rid = rid if rid is not None else f"r{self.counters['submitted']}"
        if rid in self.results or any(
                r.rid == rid for r in self._all_live()):
            raise ValueError(f"duplicate request id {rid!r}")
        req = _Req(rid, prompt, max_new, temperature, seed,
                   self.clock())
        req.trace = trace if isinstance(trace, str) and trace \
            else new_trace_id()
        req.span = new_span_id()
        req.parent = parent if isinstance(parent, str) and parent \
            else None
        req.attempt = max(0, int(attempt))
        if generated:
            # resume mid-stream: identical state to a post-eviction
            # requeue — ctx re-prefills prompt + prefix, the next
            # sample draws at token index len(generated)
            req.generated = generated
            req.ctx = np.concatenate(
                [prompt, np.asarray(generated, np.int32)])
        self.queue.append(req)
        self.counters["submitted"] += 1
        extra = {"resumed": len(generated)} if generated else {}
        self._lifecycle(req, "submit", tokens=int(tp), **extra)
        self._lifecycle(req, "queued")
        return rid

    def poll(self, rid: str) -> dict:
        """{"status": queued|running|done, "tokens": generated so far}."""
        if rid in self.results:
            return {"status": "done", "tokens": self.results[rid]}
        for r in self._all_live():
            if r.rid == rid:
                status = "queued" if r.phase == "queued" else "running"
                return {"status": status,
                        "tokens": np.asarray(r.generated, np.int32)}
        raise KeyError(rid)

    def pending(self) -> int:
        return len(self.queue) + sum(1 for s in self.slots
                                     if s is not None)

    def step(self) -> bool:
        """One scheduler tick: admissions, ONE prefill chunk (FIFO
        across prefilling requests), one decode tick over every
        decoding slot. Returns whether any work ran — decodes advance
        every step even while a long prompt prefills, which is the
        chunked-prefill no-stall contract."""
        plan = self.chaos_plan if self.chaos_plan is not None \
            else chaos.active()
        if plan is not None:
            # tick-indexed faults: a serving drill reuses the training
            # hooks — stall sleeps here (and must surface as replica
            # skew the fleet's straggler detector names — AND, tagged,
            # as the profiler capture's dominant host bucket), kill/
            # nan poison the params like a training step would
            with phase_tag("data-load"):
                plan.on_data_load(self.counters["ticks"])
                plan.on_step(self.counters["ticks"], engine=self)
        # phase tags (round 17): name the scheduler's host buckets for
        # the sampling profiler; phase_tag is a shared no-op unless a
        # profiler is running
        with phase_tag("block-alloc"):
            did = self._admit()
        with phase_tag("prefill-chunk"):
            did = self._prefill_step() or did
        with phase_tag("decode-tick"):
            did = self._decode_step() or did
        return did

    def run(self, max_steps: int | None = None) -> dict:
        """Drain: step until every submitted request finished (or
        `max_steps`, for bounded tests). Returns {rid: tokens}."""
        steps = 0
        while self.pending():
            if max_steps is not None and steps >= max_steps:
                break
            if not self.step():
                raise RuntimeError(
                    "scheduler made no progress with requests pending "
                    f"(queue={len(self.queue)}, "
                    f"free_blocks={self.alloc.n_free})")
            steps += 1
        return dict(self.results)

    def drain(self) -> bool:
        """Graceful drain: stop admitting NEW submissions (they raise
        the typed `EngineDraining`), let everything already accepted —
        queued and running — run to completion. Idempotent; returns
        True when all accepted work has finished, so a scale-down loop
        is `while not eng.drain(): eng.step()` followed by
        deregistration. Queue shedding (`on_alert`) pauses and resumes;
        drain is one-way."""
        self.draining = True
        return self.pending() == 0

    def executable_counts(self) -> dict:
        """Live jit-cache sizes of the serving entrypoints — the
        compile-count pin (`fn._cache_size`, the same counter the
        analysis retrace rule and RunTelemetry read). After warmup
        these must NOT grow as requests churn."""
        return {"decode_tick": int(_decode_tick._cache_size()),
                "prefill_chunk": int(_prefill_chunk._cache_size()),
                "sample": int(_sample_jit._cache_size())}

    # ------------------------------------------------------- lifecycle

    def _lifecycle(self, req, phase: str, **extra) -> None:
        """One phase transition on `req`'s timeline: submit -> queued
        -> admitted -> prefill (per chunk) -> decoding -> preempted ->
        requeued -> ... -> finished. Stamps a schema-v8 "lifecycle"
        metrics event (with the ms spent in the PREVIOUS phase, so
        `report.request_timeline` reconstructs the whole span
        accounting) and, when tracing is live, closes the previous
        phase as an X span on the request's named trace track —
        cross-linked to the engine tick spans via the tick counter."""
        if not self.lifecycle:
            return
        now = self.clock()
        prev = req.timeline[-1] if req.timeline else None
        entry = {"phase": phase, "wall": now, **extra}
        req.timeline.append(entry)
        if self.metrics is not None:
            rec = {"id": req.rid, "phase": phase,
                   "seq": len(req.timeline) - 1,
                   "tick": self.counters["ticks"],
                   # schema v11: the cross-process join keys — one
                   # trace per fleet request, one span per engine
                   # attempt, attempt = the cross-engine dispatch
                   # counter the (rid, attempt) reduction keys on
                   "trace": req.trace, "span": req.span,
                   "attempt": req.attempt, **extra}
            if req.parent is not None:
                rec["parent"] = req.parent
            if req.slot is not None:
                rec["slot"] = req.slot
            if prev is not None:
                rec["prev"] = prev["phase"]
                rec["ms_in_prev"] = round((now - prev["wall"]) * 1e3, 3)
            self.metrics.log(event="lifecycle", **rec)
        tr = tracer()
        if tr.level != "off":
            if req.track is None:
                req.track = tr.track(f"request {req.rid}")
            t1 = tr.now()
            if prev is not None and req.trace_t0 is not None:
                tr.complete(prev["phase"], req.trace_t0, t1,
                            tid=req.track, id=req.rid,
                            tick=self.counters["ticks"])
            req.trace_t0 = t1

    # ------------------------------------------------------- scheduler

    def _all_live(self):
        yield from (s for s in self.slots if s is not None)
        yield from self.queue

    def on_alert(self, alert: dict) -> None:
        """SLO burn-rate alert hook (`Monitor.alert_listeners`): pause
        admission while ANY rule's critical burn persists, resume when
        the LAST critical rule resolves or de-escalates. Alerts are
        per-rule state transitions, so membership is tracked per SLO
        spec — rule B resolving while rule A still burns critical must
        not release A's shed. Stamps a ledger-style `"ledger"` line
        (kind `load_shed`) at each pause/resume toggle so the goodput
        reducer can see the shed windows next to the request records."""
        slo = str(alert.get("slo"))
        if (alert.get("state") == "firing"
                and alert.get("severity") == "critical"):
            self._critical_slos.add(slo)
        else:
            self._critical_slos.discard(slo)
        want = bool(self._critical_slos)
        if want == self.admission_paused:
            return
        self.admission_paused = want
        self.counters["shed_toggles"] += 1
        if self.metrics is not None:
            self.metrics.log(event="ledger", kind="load_shed",
                             count=1 if want else 0,
                             slo=sorted(self._critical_slos)[0]
                             if want else slo)

    def headroom(self) -> dict:
        """The capacity plane's admission-headroom estimate: blocks
        still needed to finish EVERY accepted request (queued and
        running) at its max-token budget, vs what the pool can
        surrender (free + reclaimable cold). Negative headroom means
        the accepted work is overcommitted — evictions are coming
        unless requests finish early — which is the router's
        shed-before-evict placement signal. Uses submit()'s footprint
        model (tp + max_new - 1 cache positions), so a request's
        deficit falls as its table grows."""
        needed = 0
        for r in self._all_live():
            final = blocks_for(r.prompt.shape[0] + r.max_new - 1,
                               self.block_size)
            needed += max(0, final - len(r.table))
        return {"live_blocks": self.alloc.n_live,
                "blocks_needed": needed,
                "headroom_blocks": (self.alloc.n_free
                                    + self.alloc.n_cold - needed)}

    def _note_oom(self, e: OutOfBlocks) -> None:
        """Record one RECOVERED block-exhaustion event: bump the
        counter, notify the forensics listeners, stamp the typed `oom`
        ledger line. Throttled to once per tick — a blocked queue
        retrying every tick is ONE pressure episode, not a stamp per
        retry. Listeners run FIRST so the rich forensic payload (per-
        owner bytes, allocator snapshot) wins the flight recorder's
        (reason, step) dedup over the bare ledger line's trigger."""
        tick = self.counters["ticks"]
        if tick == self._oom_tick:
            return
        self._oom_tick = tick
        self.counters["oom_events"] += 1
        for fn in list(self.oom_listeners):
            try:
                fn(self, e)
            except Exception:
                pass  # a broken listener must not kill the scheduler
        if self.metrics is not None:
            extra = {"id": str(e.rid)} if e.rid is not None else {}
            self.metrics.log(event="ledger", kind="oom", tick=tick,
                             requested=e.requested, free=e.n_free,
                             cold=e.n_cold, live=e.n_live, **extra)

    def oom_forensics(self, e: OutOfBlocks | None = None,
                      top_k: int = 8) -> dict:
        """The memory flight-dump payload for this engine: the
        process-wide per-owner decomposition, top-K largest live
        arrays, backend allocator stats and host RSS
        (`telemetry/memory.forensics`) joined with the block
        allocator's snapshot, the headroom estimate, per-request
        block-table widths, and the in-flight request set. Host-side
        only — allocates no device memory, safe inside an OOM
        handler."""
        from shallowspeed_tpu.telemetry import memory as memlib

        out = memlib.forensics(top_k)
        out["allocator"] = self.alloc.snapshot()
        out["headroom"] = self.headroom()
        out["block_tables"] = {r.rid: len(r.table)
                               for r in self.slots if r is not None}
        out["in_flight"] = [r.rid for r in self._all_live()]
        if e is not None:
            out["oom"] = {"requested": e.requested, "free": e.n_free,
                          "cold": e.n_cold, "live": e.n_live,
                          "rid": e.rid}
        return out

    def _admit(self) -> bool:
        did = False
        if self.admission_paused and any(s is not None
                                         for s in self.slots):
            # shed: drain the in-flight work, admit nothing new. The
            # all-slots-empty carve-out keeps the scheduler live — a
            # pause with nothing running would wedge `run()` (no
            # progress, requests pending) without shedding any load.
            return False
        while self.queue and None in self.slots:
            req = self.queue[0]
            need = blocks_for(len(req.ctx), self.block_size)
            # prefix-cache probe: map the longest indexed aligned
            # prefix straight into the block table and start chunked
            # prefill at the divergence point. A FULLY-aligned match
            # (every block of ctx indexed) still re-prefills its last
            # token: the tail block copies-on-write into a fresh block
            # so decode can append without mutating the shared one,
            # and the final-position logits come from a real chunk.
            matched: list[int] = []
            if self.prefix is not None:
                matched = self.prefix.match(req.ctx)
                self.counters["prefix_lookups"] += 1
                self._win_prefix_lookups += 1
            m = len(matched)
            full = m > 0 and m * self.block_size == len(req.ctx)
            try:
                if matched:
                    self.alloc.acquire(matched)
                try:
                    fresh = self.alloc.alloc(need - m + (1 if full else 0),
                                             rid=req.rid)
                except OutOfBlocks:
                    if matched:          # all-or-nothing admission
                        self.alloc.release(matched)
                    raise
            except OutOfBlocks as e:
                self._note_oom(e)
                break                # wait for blocks to free
            self.queue.popleft()
            slot = self.slots.index(None)
            req.slot = slot
            if full:
                # hold the matched tail block (the CoW source) by the
                # acquire above until the copy lands in the first
                # prefill chunk; the table gets the fresh copy instead
                req.cow = (matched[-1], fresh[0])
                req.table = matched[:-1] + fresh
                req.written = len(req.ctx) - 1
            else:
                req.cow = None
                req.table = matched + fresh
                req.written = m * self.block_size
            skipped = req.written
            req.phase = "prefill"
            req.admit_seq = self._admit_counter
            self._admit_counter += 1
            req.admit_t = self.clock()
            # queue wait accumulates PER STINT (a preempted request's
            # on-device time between stints must not count as waiting)
            req.wait_s += req.admit_t - req.queued_at
            self.slots[slot] = req
            self._lifecycle(req, "admitted", slot=slot)
            if m > 0:
                req.hit_blocks += m
                req.skipped_tok += skipped
                self.counters["prefix_hits"] += 1
                self.counters["prefix_skipped_tokens"] += skipped
                self._win_prefix_hits += 1
                self._lifecycle(req, "prefill_cached", blocks=m,
                                tokens=int(skipped))
            did = True
        return did

    def _prefill_step(self) -> bool:
        pre = [r for r in self.slots
               if r is not None and r.phase == "prefill"]
        if not pre:
            return False
        req = min(pre, key=lambda r: r.admit_seq)     # FIFO
        c = self.prefill_chunk
        n_tok = min(c, len(req.ctx) - req.written)
        self._lifecycle(req, "prefill", chunk=req.written // c,
                        tokens=int(n_tok))
        tokens = np.zeros((1, c), np.int32)
        tokens[0, :n_tok] = req.ctx[req.written:req.written + n_tok]
        w = table_width(len(req.table), self.table_bucket)
        bt = np.full((1, w), SCRATCH_BLOCK, np.int32)
        bt[0, :len(req.table)] = req.table
        # copy-on-write rides the chunk as DATA on every call (scratch
        # self-copy when there is nothing to copy) — zero executables
        cow = req.cow if req.cow is not None \
            else (SCRATCH_BLOCK, SCRATCH_BLOCK)
        logits, self.pools = _prefill_chunk(
            self.params, self.pools, tokens, np.int32(req.written),
            np.int32(n_tok), bt, np.int32(cow[0]), np.int32(cow[1]),
            cfg=self.cfg)
        if req.cow is not None:
            # the copy landed: drop the reference that kept the shared
            # source block alive for it
            self.alloc.release([req.cow[0]])
            req.cow = None
        req.written += n_tok
        self.counters["prefill_chunks"] += 1
        if req.written == len(req.ctx):
            # prompt complete: sample this request's next token (token
            # index len(generated) — 0 for a fresh request, the
            # continuation index after a preemption) from the last
            # true position's logits, exactly like generate()'s
            # post-prefill sample
            with phase_tag("sampling"):
                tok = _sample_jit(
                    logits, np.asarray([req.temp], np.float32),
                    np.asarray([req.seed], np.uint32),
                    np.asarray([len(req.generated)], np.int32),
                    top_k=self.top_k, top_p=self.top_p)
            req.phase = "decode"
            self._lifecycle(req, "decoding")
            self._append_token(req, int(np.asarray(tok)[0]))
        return True

    def _decode_step(self) -> bool:
        for req in [r for r in self.slots
                    if r is not None and r.phase == "decode"]:
            if req.slot is not None:          # not evicted meanwhile
                self._ensure_block(req)
        actives = [r for r in self.slots
                   if r is not None and r.phase == "decode"]
        if not actives:
            return False
        s = self.max_slots
        bs = self.block_size
        # speculative drafts claim the tick's FREE rows (empty slots
        # and prefilling requests' idle rows) — occupancy is data, so
        # drafting costs zero executables and zero extra tick time
        drafts: dict[str, tuple] = {}
        if self.spec_k > 0:
            free = [i for i in range(s)
                    if i not in {r.slot for r in actives}]
            for r in sorted(actives, key=lambda r: r.admit_seq):
                if not free:
                    break
                cap = min(self.spec_k, len(free),
                          r.max_new - len(r.generated) - 1)
                if cap <= 0:
                    continue
                d = self._grow_for_drafts(r, self._propose(r, cap))
                if d:
                    drafts[r.rid] = (r, [(free.pop(0), t) for t in d])
        tok = np.zeros(s, np.int32)
        pos = np.zeros(s, np.int32)
        temp = np.zeros(s, np.float32)
        seeds = np.zeros(s, np.uint32)
        idx = np.zeros(s, np.int32)
        w = table_width(max(len(r.table) for r in actives),
                        self.table_bucket)
        bt = np.full((s, w), SCRATCH_BLOCK, np.int32)
        for r in actives:
            tok[r.slot] = r.last_tok
            pos[r.slot] = r.written
            temp[r.slot] = r.temp
            seeds[r.slot] = r.seed
            idx[r.slot] = len(r.generated)
            bt[r.slot, :len(r.table)] = r.table
        for r, assigned in drafts.values():
            # draft row j: the j-th draft token at position written+j,
            # sampling at oracle token index len(generated)+j — the
            # same fold_in schedule the solo stream uses at that index
            for j, (row, dtok) in enumerate(assigned, start=1):
                tok[row] = dtok
                pos[row] = r.written + j
                temp[row] = r.temp
                seeds[row] = r.seed
                idx[row] = len(r.generated) + j
                bt[row, :len(r.table)] = r.table
        if w not in self._tick_widths:
            # FIRST tick at this width bucket compiles a fresh
            # executable (geometric bucketing keeps the count O(log
            # max_len)); later returns to the width hit the jit cache,
            # so only first visits stamp — a phantom stamp per
            # width flip under alternating traffic would over-book
            # compile pauses that never happened. The warmup width
            # (empty seen-set) is booked as compile, not a rebucket.
            if self._tick_widths and self.metrics is not None:
                self.metrics.log(event="ledger", kind="table_rebucket",
                                 count=1, prev_width=self._last_width,
                                 width=int(w),
                                 tick=self.counters["ticks"])
            self._tick_widths.add(w)
        self._last_width = w
        nxt, self.pools = _decode_tick(
            self.params, self.pools, tok, pos, bt, temp, seeds, idx,
            cfg=self.cfg, top_k=self.top_k, top_p=self.top_p,
            attn=self.attn_impl)
        nxt = np.asarray(nxt)
        self.counters["ticks"] += 1
        self._last_touched = sum(
            blocks_for(r.written + 1
                       + len(drafts.get(r.rid, (None, ()))[1]), bs)
            for r in actives)
        emitted = 0
        for r in actives:
            # speculation tallies accrue BEFORE the appends: an
            # accepted final draft can finish the request, and the
            # "request" record stamped at that instant must already
            # carry this tick's drafted/accepted counts
            assigned = drafts.get(r.rid, (None, ()))[1]
            if assigned:
                r.n_drafted += len(assigned)
                self.counters["spec_drafted"] += len(assigned)
                self._win_drafted += len(assigned)
            tok_next = int(nxt[r.slot])
            r.written += 1
            self._append_token(r, tok_next)
            emitted += 1
            for row, dtok in assigned:
                # accept while the draft equals the oracle draw; the
                # next row's logits are then the TRUE logits at the
                # advanced context, so its draw is the oracle's too
                if r.rid in self.results or dtok != tok_next:
                    break
                tok_next = int(nxt[row])
                r.n_accepted += 1
                self.counters["spec_accepted"] += 1
                self._win_accepted += 1
                r.written += 1
                self._append_token(r, tok_next)
                emitted += 1
        self._win_tokens += emitted
        with phase_tag("logging"):
            self._maybe_log()
        return True

    # ------------------------------------------------- spec decoding

    def _propose(self, req, k: int) -> list:
        """Self-drafting n-gram prompt-lookup proposer: find the most
        recent EARLIER occurrence of the context's trailing n-gram
        (longest n first, n <= spec_ngram) and draft the k tokens that
        followed it. No draft model and no device work — the draft
        source is the request's own prompt + generated stream, which
        is exactly where repeated spans (code, templates, copied
        entities) live. O(spec_ngram) dict lookups per call: the
        occurrence index is built once per request and maintained
        O(spec_ngram) per appended token (`_spec_note`) — a per-tick
        rescan would cost O(context) host time per request, growing
        with every generated token."""
        ctx, idx = self._spec_state(req)
        n_ctx = len(ctx)
        for n in range(min(self.spec_ngram, n_ctx - 1), 0, -1):
            ent = idx.get(tuple(ctx[n_ctx - n:]))
            if ent is None:
                continue
            # the index's latest entry is the tail itself (indexed
            # when its last token arrived) — the draft source is the
            # most recent occurrence BEFORE it
            start = ent[0] if ent[0] != n_ctx - n else ent[1]
            if start is not None:
                return ctx[start + n:start + n + k]
        return []

    def _spec_state(self, req) -> tuple:
        """The request's draft-lookup state, built lazily on first
        use: `ctx_ids` (prompt + generated as a plain list, appended
        in `_append_token`) and `spec_idx`, mapping each n-gram tuple
        (n <= spec_ngram) to its (latest, previous) start positions.
        Survives preemption unchanged — eviction re-prefills the SAME
        logical stream."""
        if req.spec_idx is None:
            req.ctx_ids = req.prompt.tolist() + list(req.generated)
            req.spec_idx = {}
            for j in range(len(req.ctx_ids)):
                self._spec_note(req, j)
        return req.ctx_ids, req.spec_idx

    def _spec_note(self, req, j: int) -> None:
        """Index every n-gram ending at position `j` of the context
        (latest occurrence wins; the one it displaces is kept as the
        'previous' slot `_propose` falls back to when latest is the
        trailing gram itself)."""
        ctx = req.ctx_ids
        for n in range(1, self.spec_ngram + 1):
            start = j - n + 1
            if start < 0:
                break
            gram = tuple(ctx[start:j + 1])
            ent = req.spec_idx.get(gram)
            req.spec_idx[gram] = (start,
                                  None if ent is None else ent[0])

    def _grow_for_drafts(self, req, d: list) -> list:
        """Grow `req`'s table to cover its draft rows' write positions
        WITHOUT evicting anyone — drafts are opportunistic, so on pool
        pressure they trim to the blocks already held instead of
        preempting real work (contrast `_ensure_block`)."""
        if not d:
            return d
        grow = blocks_for(req.written + len(d) + 1,
                          self.block_size) - len(req.table)
        if grow > 0:
            try:
                req.table.extend(self.alloc.alloc(grow, rid=req.rid))
            except OutOfBlocks as e:
                self._note_oom(e)
                cap = len(req.table) * self.block_size - 1 - req.written
                d = d[:max(0, cap)]
        return d

    def _ensure_block(self, req) -> bool:
        """Grow `req`'s table to cover its next write position,
        evicting the newest-admitted running request on OOM (possibly
        `req` itself). Returns whether `req` is still running."""
        while req.written // self.block_size >= len(req.table):
            try:
                with phase_tag("block-alloc"):
                    req.table.extend(self.alloc.alloc(1, rid=req.rid))
            except OutOfBlocks as e:
                self._note_oom(e)
                live = [r for r in self.slots if r is not None]
                victim = max(live, key=lambda r: r.admit_seq)
                if victim is req and len(live) == 1:
                    # submit() guarantees a lone request fits — reaching
                    # here means the accounting broke
                    raise RuntimeError(
                        "allocator invariant violated: a lone request "
                        "cannot grow its table") from None
                self._evict(victim)
                if victim is req:
                    return False
        return True

    def _evict(self, req) -> None:
        """Preempt: release the block references NOW, re-queue at the
        front. The request keeps its generated tokens and sampling
        indices — on re-admission it re-prefills prompt + generated
        (re-probing the prefix index, so a still-cached prefix skips
        again) and continues its stream exactly where it stopped.
        Shared blocks other requests still reference stay live; only
        this request's references drop."""
        if req.cow is not None:          # pending CoW source reference
            self.alloc.release([req.cow[0]])
            req.cow = None
        self.alloc.release(req.table)
        req.table = []
        req.written = 0
        req.ctx = np.concatenate(
            [req.prompt, np.asarray(req.generated, np.int32)]) \
            if req.generated else req.prompt
        self._lifecycle(req, "preempted",
                        tokens=len(req.generated))
        self.slots[req.slot] = None
        req.slot = None
        req.phase = "queued"
        req.queued_at = self.clock()
        req.n_preempt += 1
        self.counters["preempted"] += 1
        self.queue.appendleft(req)
        self._lifecycle(req, "requeued")

    def _append_token(self, req, tok: int) -> None:
        req.generated.append(tok)
        if req.spec_idx is not None:  # keep the draft index current
            req.ctx_ids.append(tok)
            self._spec_note(req, len(req.ctx_ids) - 1)
        req.last_tok = tok
        if req.first_tok_t is None:
            req.first_tok_t = self.clock()
        if len(req.generated) >= req.max_new:
            self._finish(req)

    def _finish(self, req) -> None:
        # donate the sealed aligned prefix to the cache BEFORE the
        # release: indexed blocks whose refcount hits zero park on the
        # cold LRU list (still matchable, reclaimed under pressure)
        # instead of returning to the free list. Only blocks fully
        # covered by PREFILL-written context are sealed — decode-
        # written positions live past len(ctx) and never land in a
        # donated block.
        if self.prefix is not None and req.table:
            sealed = min(req.written, len(req.ctx)) // self.block_size
            if sealed > 0:
                self.prefix.insert(req.ctx, req.table[:sealed])
        if req.cow is not None:
            self.alloc.release([req.cow[0]])
            req.cow = None
        self.alloc.release(req.table)
        req.table = []
        self._lifecycle(req, "finished", tokens=len(req.generated))
        if self.lifecycle:
            # bounded retention (FIFO on dict insertion order): a
            # long-running server must not grow one timeline per
            # request forever; the JSONL stream is the full record
            self.timelines[req.rid] = req.timeline
            while len(self.timelines) > TIMELINE_CAP:
                self.timelines.pop(next(iter(self.timelines)))
        self.slots[req.slot] = None
        self.results[req.rid] = np.asarray(req.generated, np.int32)
        self.counters["finished"] += 1
        now = self.clock()
        rec = {
            "id": req.rid,
            "ttft_ms": round((req.first_tok_t - req.arrival) * 1e3, 3),
            "tokens_in": int(req.prompt.shape[0]),
            "tokens_out": len(req.generated),
            "e2e_ms": round((now - req.arrival) * 1e3, 3),
            "wait_ms": round(req.wait_s * 1e3, 3),
            "queue_depth": len(self.queue),
            "preempted": req.n_preempt,
            # schema v11: trace context on the completion record too,
            # so a replica log's request line joins its own lifecycle
            # stream and the router's fleet-edge record by trace id
            "trace": req.trace, "span": req.span,
            "attempt": req.attempt,
        }
        if len(req.generated) > 1:
            rec["tpot_ms"] = round(
                (now - req.first_tok_t) * 1e3 / (len(req.generated) - 1),
                3)
        if self.spec_k > 0:  # schema v9: per-request speculation record
            rec["spec_drafted"] = req.n_drafted
            rec["spec_accepted"] = req.n_accepted
        if self.prefix is not None:  # schema v14: prefix-cache record
            rec["prefix_hit_blocks"] = req.hit_blocks
            rec["prefill_skipped_tokens"] = req.skipped_tok
        self.request_records.append(rec)
        if self.metrics is not None:
            self.metrics.log(event="request", **rec)

    def _maybe_log(self) -> None:
        if (self.metrics is None or self.log_every <= 0
                or self.counters["ticks"] % self.log_every):
            return
        now = self.clock()
        dt = max(now - self._win_t, 1e-9)
        bpt = paged_read_bytes_per_tick(
            self.params, self.cfg, self._last_touched, self.block_size,
            self.max_slots, self.kv_quant, p_bytes=self._p_bytes)
        ticks_per_sec = self.log_every / dt
        extra = {}
        if self.spec_k > 0:  # schema v9: windowed speculation telemetry
            extra = {"spec_drafted": self._win_drafted,
                     "spec_accepted": self._win_accepted,
                     "spec_accept_rate": round(
                         self._win_accepted / self._win_drafted, 4)
                     if self._win_drafted else 0.0}
        if self.prefix is not None:  # schema v14: prefix-cache gauges
            extra.update(
                prefix_hit_rate=round(
                    self._win_prefix_hits / self._win_prefix_lookups, 4)
                if self._win_prefix_lookups else 0.0,
                cold_blocks=self.alloc.n_cold,
                prefix_blocks=len(self.prefix))
        hr = self.headroom()      # schema v15: capacity-plane gauges
        self.metrics.log(
            event="generate",
            tokens_per_sec=round(self._win_tokens / dt, 2),
            queue_depth=len(self.queue),
            active_slots=sum(1 for r in self.slots if r is not None),
            free_blocks=self.alloc.n_free,
            blocks_touched=self._last_touched,
            bytes_per_tick=int(bpt),
            hbm_gbps=round(ticks_per_sec * bpt / 1e9, 4),
            live_blocks=hr["live_blocks"],
            blocks_needed=hr["blocks_needed"],
            headroom_blocks=hr["headroom_blocks"],
            **extra)
        self._win_tokens = 0
        self._win_drafted = 0
        self._win_accepted = 0
        self._win_prefix_lookups = 0
        self._win_prefix_hits = 0
        self._win_t = now
