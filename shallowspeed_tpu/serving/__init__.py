"""Serving runtime: paged KV cache + continuous-batching decode server.

ROADMAP item 1 (round 11). `cache.py` owns the memory model (block
pools, the free-list `BlockAllocator`, gathered-table reads, the
live-blocks HBM byte model); `engine.py` owns the compiled decode
tick / chunked prefill and the scheduler (admission, preemption,
per-request SLO telemetry). `serve.py` at the repo root is the CLI
driver; `tests/test_serving.py` pins stream parity against
`generate()` and the zero-recompile churn contract.
"""

from shallowspeed_tpu.serving.cache import (BlockAllocator,  # noqa: F401
                                            OutOfBlocks, blocks_for,
                                            init_block_pool,
                                            paged_read_bytes_per_tick)
from shallowspeed_tpu.serving.engine import (EngineDraining,  # noqa: F401
                                             ServingEngine, table_width)

__all__ = ["BlockAllocator", "EngineDraining", "OutOfBlocks",
           "ServingEngine", "blocks_for", "init_block_pool",
           "paged_read_bytes_per_tick", "table_width"]
