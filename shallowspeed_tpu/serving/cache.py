"""Paged KV cache: block pools, a host-side free-list allocator, and
the gathered-table read path.

The contiguous decode cache (`models/kv_cache.init_kv_cache`) sizes one
(B, Hkv, slots, hd) buffer per request batch — fine for one `generate()`
call, useless for a server where requests of different lengths join and
leave continuously: every admission would recompile, and every short
request would pay the longest request's slots. The paged layout instead
carves each layer's cache into fixed `(n_blocks, Hkv, block_size, hd)`
POOLS (vLLM's PagedAttention memory model, arXiv 2309.06180, rebuilt
jit-first): a request owns an ordered list of block ids (its *block
table*), the pools are donated through every compiled tick (no copies,
stable buffers), and attention reads through a GATHERED view of the
table — `pool[bt]` — masked by position. Appending a token allocates at
most one block; freeing a finished request returns its blocks in O(1);
fragmentation cannot exist because any free block serves any request.

Block 0 is RESERVED as a scratch sink: compiled programs run at a fixed
slot capacity, so inactive slots (and the padded tail of a prefill
chunk) still execute their cache write — they are steered to block 0,
which no live table ever contains. That keeps the tick free of
host-side branching without ever corrupting a live block.

int8 pools mirror the contiguous int8 cache exactly (same per-(row,
head, position) absmax scales via `kv_cache.quantize_kv`), so the paged
sweep halves its bytes the same way.
"""

from __future__ import annotations

import hashlib

import jax.numpy as jnp
import numpy as np

from shallowspeed_tpu.models import transformer as T
from shallowspeed_tpu.models.kv_cache import KV_QUANT_MODES, quantize_kv

SCRATCH_BLOCK = 0


class OutOfBlocks(RuntimeError):
    """The free list is empty. The scheduler's preemption policy (evict
    the newest running request, re-queue it with its blocks freed)
    catches this; it never escapes a `ServingEngine.step`.

    Typed payload (round 20, the memory observatory): handlers and
    forensics read `requested`/`n_free`/`n_cold`/`n_live`/`rid`
    directly instead of string-matching the message. The message keeps
    its historical "need N blocks, F free + C cold" shape."""

    def __init__(self, requested: int, n_free: int = 0, n_cold: int = 0,
                 n_live: int = 0, rid=None):
        self.requested = int(requested)
        self.n_free = int(n_free)
        self.n_cold = int(n_cold)
        self.n_live = int(n_live)
        self.rid = rid
        msg = (f"need {self.requested} blocks, {self.n_free} free + "
               f"{self.n_cold} cold")
        if rid is not None:
            msg += f" (request {rid!r})"
        super().__init__(msg)


def blocks_for(n_tokens: int, block_size: int) -> int:
    """Blocks needed to hold `n_tokens` cache positions."""
    return max(0, -(-int(n_tokens) // int(block_size)))


def init_block_pool(cfg: T.TransformerConfig, n_blocks: int,
                    block_size: int, kv_quant: str = ""):
    """Per-layer paged K/V pools (n_blocks, Hkv, block_size, hd),
    zero-filled; int8 pools add the (n_blocks, Hkv, block_size, 1) f32
    scale planes, matching `init_kv_cache`'s int8 variant per-position.
    Layout is the contiguous cache's head-major sweep with the slot
    axis folded into (block id, offset)."""
    if kv_quant not in KV_QUANT_MODES:
        raise ValueError(
            f"unsupported kv_quant={kv_quant!r}; expected one of "
            f"{KV_QUANT_MODES} ('' = pool in the compute dtype)")
    if n_blocks < 2:
        raise ValueError(f"n_blocks={n_blocks} leaves no usable blocks "
                         f"past the reserved scratch block")
    dt = cfg.compute_dtype or cfg.dtype
    shape = (n_blocks, cfg.kv_heads, block_size, cfg.head_dim)
    if kv_quant:
        sshape = shape[:3] + (1,)
        return [{"k": jnp.zeros(shape, jnp.int8),
                 "k_s": jnp.zeros(sshape, jnp.float32),
                 "v": jnp.zeros(shape, jnp.int8),
                 "v_s": jnp.zeros(sshape, jnp.float32)}
                for _ in range(cfg.n_layers)]
    return [{"k": jnp.zeros(shape, dt), "v": jnp.zeros(shape, dt)}
            for _ in range(cfg.n_layers)]


class BlockAllocator:
    """Host-side refcounted free list over one pool's block ids.

    Pure bookkeeping — no device arrays. Every live block carries a
    refcount: `alloc` mints fresh blocks at refcount 1, `acquire` adds
    a reference to a block another holder already owns (prefix-cache
    sharing), `release`/`free` drops one reference per listed id. A
    block whose refcount hits zero returns to the free list — unless a
    `PrefixIndex` still remembers its content, in which case it parks
    on the COLD list (LRU-ordered, oldest first) where it stays
    matchable until pool pressure reclaims it: `alloc` drains cold
    blocks (dropping their index entries) before `OutOfBlocks` fires.

    Invariants (pinned in tests/test_serving.py):
    `n_free + n_live + n_cold == n_usable` always; refcounts are
    per-holder, so at drain `n_live == 0`; `release` rejects ids whose
    listed multiplicity exceeds the current refcount — including
    duplicates WITHIN one call (`free([i, i])` of a once-held block
    raises instead of double-appending `i` to the free list); block 0
    (scratch) is never handed out."""

    def __init__(self, n_blocks: int, index: "PrefixIndex | None" = None):
        if n_blocks < 2:
            raise ValueError(f"n_blocks={n_blocks} leaves no usable "
                             f"blocks past the reserved scratch block")
        self.n_blocks = int(n_blocks)
        # LIFO free list: recently freed (still-warm) blocks are reused
        # first; ids 1..n-1 — block 0 is the scratch sink
        self._free = list(range(self.n_blocks - 1, 0, -1))
        self._ref: dict[int, int] = {}
        # insertion-ordered dict as the LRU cold list: front = oldest
        # (first reclaimed), back = most recently parked
        self._cold: dict[int, None] = {}
        self.index = index
        self.cold_reclaims = 0
        # high-water of n_live over the allocator's lifetime (round 20
        # capacity accounting: tokens-per-peak-live-block in bench)
        self.peak_live = 0

    @property
    def n_usable(self) -> int:
        return self.n_blocks - 1

    @property
    def n_free(self) -> int:
        return len(self._free)

    @property
    def n_live(self) -> int:
        return len(self._ref)

    # back-compat alias (pre-refcount callers/tests)
    n_allocated = n_live

    @property
    def n_cold(self) -> int:
        return len(self._cold)

    def refcount(self, bid: int) -> int:
        return self._ref.get(bid, 0)

    def alloc(self, n: int, rid=None) -> list[int]:
        """Mint `n` fresh blocks at refcount 1, or raise OutOfBlocks
        WITHOUT partial allocation (all-or-nothing, so a failed
        admission never leaks). Under pressure, cold cached blocks are
        reclaimed LRU-first (their index entries dropped) before the
        raise — referenced blocks are never touched. `rid` (the
        requesting request id, when the caller has one) rides the
        typed OutOfBlocks payload into the OOM forensics."""
        if n < 0:
            raise ValueError(f"alloc({n})")
        if n > len(self._free) + len(self._cold):
            raise OutOfBlocks(n, n_free=len(self._free),
                              n_cold=len(self._cold),
                              n_live=len(self._ref), rid=rid)
        while len(self._free) < n:
            self._reclaim_one()
        ids = [self._free.pop() for _ in range(n)]
        for i in ids:
            self._ref[i] = 1
        self.peak_live = max(self.peak_live, len(self._ref))
        return ids

    def _reclaim_one(self) -> None:
        bid = next(iter(self._cold))          # oldest parked = LRU
        del self._cold[bid]
        if self.index is not None:
            self.index.drop_block(bid)
        self._free.append(bid)
        self.cold_reclaims += 1

    def acquire(self, ids) -> None:
        """Add one reference per listed id to blocks that are live or
        cold (prefix-cache hit). Cold blocks are revived off the LRU
        list. All-or-nothing: validates before mutating."""
        ids = list(ids)
        bad = [i for i in ids if i not in self._ref and i not in self._cold]
        if bad:
            raise ValueError(f"acquire() of unknown block(s) {bad}")
        for i in ids:
            self._cold.pop(i, None)
            self._ref[i] = self._ref.get(i, 0) + 1
        self.peak_live = max(self.peak_live, len(self._ref))

    def release(self, ids) -> None:
        """Drop one reference per listed id. At refcount zero the block
        parks cold if the index still maps its content, else returns to
        the free list. Rejects (before any mutation) ids whose listed
        multiplicity exceeds the current refcount — the duplicate-id
        double-free of old `free([i, i])` raises here."""
        ids = list(ids)
        counts: dict[int, int] = {}
        for i in ids:
            counts[i] = counts.get(i, 0) + 1
        bad = [i for i, c in counts.items() if self._ref.get(i, 0) < c]
        if bad:
            raise ValueError(
                f"release() of unallocated/over-released block(s) "
                f"{sorted(bad)}")
        for i in ids:
            self._ref[i] -= 1
            if self._ref[i] == 0:
                del self._ref[i]
                if self.index is not None and self.index.has_block(i):
                    self._cold[i] = None      # park: most-recent at back
                else:
                    self._free.append(i)

    # `free` kept as the historical name for dropping ownership
    free = release

    def snapshot(self) -> dict:
        """Point-in-time occupancy for the capacity timeline and OOM
        forensics. `consistent` restates the allocator invariant
        (n_free + n_live + n_cold == n_usable) so a dump self-reports
        bookkeeping corruption."""
        return {"n_blocks": self.n_blocks, "n_usable": self.n_usable,
                "n_free": self.n_free, "n_live": self.n_live,
                "n_cold": self.n_cold, "peak_live": self.peak_live,
                "cold_reclaims": self.cold_reclaims,
                "consistent": (self.n_free + self.n_live + self.n_cold
                               == self.n_usable)}


def chunk_hashes(tokens, block_size: int) -> list[bytes]:
    """Chained content hashes of the FULL block-aligned chunks of
    `tokens`: hash k = blake2b(hash k-1 || tokens[k*bs:(k+1)*bs]), so a
    chunk's hash pins the entire prefix through it — two prompts share
    hash k iff their first (k+1)*bs tokens are identical. The partial
    tail (len % bs != 0 remainder) is never hashed: prefix hits are
    granular to whole blocks. Shared by the engine-side `PrefixIndex`
    and the router's sticky-affinity fingerprints so both sides agree
    on chunk identity. blake2b-128 keyed by content, not Python
    `hash()` — stable across processes and collision-safe at fleet
    scale."""
    toks = np.asarray(tokens, dtype=np.int64)
    bs = int(block_size)
    out: list[bytes] = []
    h = b""
    for k in range(len(toks) // bs):
        h = hashlib.blake2b(h + toks[k * bs:(k + 1) * bs].tobytes(),
                            digest_size=16).digest()
        out.append(h)
    return out


class PrefixIndex:
    """Content-addressed map from chained chunk hashes to block ids.

    `match(tokens)` walks the chain front-to-back and returns the block
    ids of the longest indexed aligned prefix (stops at the first
    miss). `insert` registers a finished request's sealed prefix blocks
    first-writer-wins: a chunk hash already mapped keeps its existing
    block (the duplicate block stays unindexed and frees normally), so
    one content never aliases two blocks. `drop_block` is the
    allocator's cold-reclaim hook — dropping a parent makes every
    descendant chain-unreachable via `match` even though the child
    entries linger until their own reclaim (harmless: match walks
    parent-first)."""

    def __init__(self, block_size: int):
        self.block_size = int(block_size)
        self._blocks: dict[bytes, int] = {}    # chain hash -> block id
        self._hash_of: dict[int, bytes] = {}   # block id -> chain hash
        self.hits = 0
        self.misses = 0

    def __len__(self) -> int:
        return len(self._blocks)

    def has_block(self, bid: int) -> bool:
        return bid in self._hash_of

    def match(self, tokens) -> list[int]:
        ids: list[int] = []
        for h in chunk_hashes(tokens, self.block_size):
            bid = self._blocks.get(h)
            if bid is None:
                break
            ids.append(bid)
        return ids

    def insert(self, tokens, table) -> int:
        """Map the leading `len(table)` full chunks of `tokens` to the
        given block ids (first-writer-wins). Returns how many NEW
        entries landed."""
        new = 0
        for k, h in enumerate(chunk_hashes(tokens, self.block_size)):
            if k >= len(table):
                break
            bid = int(table[k])
            if h in self._blocks or bid in self._hash_of:
                continue
            self._blocks[h] = bid
            self._hash_of[bid] = h
            new += 1
        return new

    def drop_block(self, bid: int) -> None:
        h = self._hash_of.pop(bid, None)
        if h is not None:
            self._blocks.pop(h, None)


def gather_table(pool_blk, bt):
    """Read one layer's cache through a block table.

    pool_blk: {"k"/"v": (N, Hkv, bs, hd)[, "k_s"/"v_s": (N, Hkv, bs, 1)]}
    bt: (rows, W) int32 block ids (padding rows/tail point at the
    scratch block — the caller's position mask never admits them).
    Returns the contiguous-cache view {"k"/"v": (rows, Hkv, W*bs, hd),
    ...} that `kv_cache.masked_attention` consumes: gathered position
    j IS absolute position j because tables are ordered."""
    rows, w = bt.shape
    out = {}
    for name, leaf in pool_blk.items():
        n, hkv, bs, tail = leaf.shape
        g = leaf[bt]                           # (rows, W, Hkv, bs, tail)
        out[name] = jnp.swapaxes(g, 1, 2).reshape(rows, hkv, w * bs,
                                                  tail)
    return out


def write_rows(pool_blk, k_rows, v_rows, blk_ids, offs, quant: bool):
    """Scatter per-row single-token K/V into one layer's pools.

    k_rows/v_rows: (rows, Hkv, hd) in compute dtype; blk_ids/offs:
    (rows,) int32 destination (block id, in-block offset). Rows steered
    to the scratch block may collide — by construction nothing ever
    reads scratch, so the unspecified duplicate-scatter winner is
    irrelevant. Quantization matches `kv_cache.cache_write`'s int8
    path value-for-value (same absmax-over-hd scales)."""
    if quant:
        kq, ks = quantize_kv(k_rows[:, :, None, :])   # (rows,Hkv,1,hd)
        vq, vs = quantize_kv(v_rows[:, :, None, :])
        upd = {"k": kq[:, :, 0], "k_s": ks[:, :, 0],
               "v": vq[:, :, 0], "v_s": vs[:, :, 0]}
    else:
        upd = {"k": k_rows.astype(pool_blk["k"].dtype),
               "v": v_rows.astype(pool_blk["v"].dtype)}
    return {name: pool_blk[name].at[blk_ids, :, offs, :].set(val)
            for name, val in upd.items()}


# ------------------------------------------------ per-tick HBM model
#
# `models/generate.decode_read_bytes_per_token` prices one contiguous
# decode step: params + the FULL cache sweep. The paged tick's useful
# sweep is only the LIVE blocks its requests touch — the number below
# is the per-tick generalization the serving progress lines report
# (the gathered table also reads its bucket-padding blocks; that
# padding is the bucketing tax, reported separately as the ratio).


def param_read_bytes(params, cfg: T.TransformerConfig) -> int:
    """Bytes one decode pass reads for the parameters alone, at the
    PER-LEAF dtypes decode actually consumes after `cast_params`
    (eval_shape — no on-device copy): float leaves at the compute
    dtype, quantized-storage leaves (int8/fp8 `Wq` + f32 `Ws` scales,
    `T.quantize_weights`) at their storage dtypes — cast_params skips
    them, so an int8-weight model prices at ~0.5x its bf16 self. One
    model can mix int8 weights, f32 scales, bf16 embeddings, and int8
    KV (priced separately below) in a single accounting. Pinned in
    tests/test_serving.py against the traced decode tick's own param
    invar bytes (the walker pin, same trick as
    `decode_read_bytes_per_token` in PR 5). Constant for an engine's
    lifetime: callers on a hot path compute it once and pass it back
    in."""
    import jax

    from shallowspeed_tpu.analysis.walker import aval_bytes

    cast = jax.eval_shape(lambda p: T.cast_params(p, cfg.compute_dtype),
                          params)
    return int(sum(aval_bytes(l) for l in
                   jax.tree_util.tree_leaves(cast)))


def paged_read_bytes_per_tick(params, cfg: T.TransformerConfig,
                              blocks_touched: int, block_size: int,
                              n_rows: int, kv_quant: str = "",
                              p_bytes: int | None = None) -> int:
    """HBM READ bytes one decode tick usefully moves: every param leaf
    (at its ACTUAL post-cast dtype — int8/fp8 weights and f32 scales
    included, see `param_read_bytes`) + the K/V bytes of the live
    blocks the tick's active requests attend over (+ int8 scale
    planes) + the token ids. `blocks_touched` = sum over active rows
    of blocks_for(context_len) — the live-blocks generalization of the
    contiguous model's full-cache sweep. Pass a precomputed `p_bytes`
    (`param_read_bytes`) on hot paths — the param term never changes.

    This is the byte model behind the fast-decode gates: the
    int8-weight tick must price at <= 0.55x its bf16 baseline (pinned
    in tests/test_serving.py against walker-traced invar bytes), and
    the serving progress lines' hbm_gbps derives from it."""
    import numpy as np

    if p_bytes is None:
        p_bytes = param_read_bytes(params, cfg)
    kv_itemsize = (1 if kv_quant == "int8"
                   else np.dtype(cfg.compute_dtype or cfg.dtype).itemsize)
    per_block = 2 * cfg.kv_heads * block_size * cfg.head_dim * kv_itemsize
    if kv_quant == "int8":
        per_block += 2 * cfg.kv_heads * block_size * 4   # f32 scales
    return (p_bytes + cfg.n_layers * int(blocks_touched) * per_block
            + n_rows * 4)
