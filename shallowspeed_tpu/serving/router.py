"""Fault-tolerant fleet front-end: SLO-aware routing over N serving
replicas, with replica failure invisible to clients.

One `ServingEngine` (PR 7) serves a stream; a fleet of them needed
three things nothing provided: something that *routes* requests,
something that survives a replica dying mid-decode, and something
that closes the scale-up/down loop. This module is all three, built
from parts that already exist — the FleetCollector (PR 9) is the
observation surface, `monitor.SloRule` the dual-window burn signal,
`elastic.RestartPolicy` the classified-backoff respawn machinery, and
the engine's evict-newest continuation (PR 7) the failover mechanism:

- **SLO-aware dispatch.** Admission is weighted by each replica's
  polled ``/status.json`` — queue depth, active slots, free blocks,
  ttft p50 — read from the FleetCollector's per-replica summaries
  (the router CONSUMES the collector, it does not re-poll), plus the
  router's own in-flight count per replica. Lowest score wins;
  deterministic tie-break by name.
- **Request resilience.** Every request carries an optional deadline
  (absolute e2e cap — typed failure past it) and a progress timeout
  (no new tokens for `request_timeout` seconds → failover). On
  replica death or timeout the request is **re-dispatched seeded and
  idempotent**: the prompt plus every token already received
  re-prefills on a fresh replica (`ServingEngine.submit(generated=)`)
  and sampling continues at token index len(generated) — because
  token i always draws from ``fold_in(PRNGKey(seed), i)``, the
  continued stream is TOKEN-IDENTICAL to the solo `generate()`
  oracle, the same mechanism as the engine's evict-newest requeue,
  now across process boundaries. Each re-dispatch stamps a schema-v10
  ``"failover"`` event.
- **Circuit breakers + fleet-edge backpressure.** One breaker per
  replica: consecutive call failures trip it open (replica death
  force-opens it), it cools down with seeded jitter (doubling up to a
  cap), then allows jittered **half-open probes** — the progress poll
  doubles as the probe, so a recovered replica is re-admitted by the
  first successful poll and traffic returns only to ``closed``
  breakers. When every breaker is open (or every replica is down or
  draining) or the router queue exceeds its budget, `submit()` raises
  the typed `FleetOverloaded` carrying ``retry_after`` — backpressure
  at the fleet edge instead of silent queue growth.
- **Replica lifecycle.** Replicas are spawned by a caller-provided
  factory (subprocess `serve.py --serve` handles in production,
  in-process engines for canaries/bench). Failures are classified
  with elastic.py's taxonomy (crash / hang via stale heartbeat /
  numeric via heartbeat status / clean) and respawned on
  `elastic.RestartPolicy`'s per-class jittered backoff; every
  detection→ready interval stamps a ``restart_downtime`` ledger line
  with its class AND replica, which `--goodput` reduces to
  per-replica MTTR and fleet availability. Scale-down is a graceful
  drain: stop dispatching, `drain()` the replica (it finishes
  in-flight work), then deregister it from the collector — zero
  dropped requests.
- **Burn-driven autoscaling.** The router feeds its OWN observed
  ttft (submit → first token, fleet-edge — routing and failover
  delays included, which is the number users feel) into
  `monitor.SloRule`'s dual-window evaluator; a critical burn
  sustained for `scale_hold_s` spawns a replica (schema-v10
  ``"scale"`` event), a fleet idle for `idle_drain_s` drains one,
  bounded by [min_replicas, max_replicas] with a cool-down between
  decisions.

Everything the router decides lands in its metrics JSONL: ``"route"``
per dispatch, ``"failover"`` per re-dispatch, ``"scale"`` per
autoscale decision, breaker transitions as ``"ledger"`` lines
(kind="breaker", state=open/half_open/closed), restart downtime with
replica + fail_class, and a fleet-edge ``"request"`` record per
completion — so ``python -m shallowspeed_tpu.telemetry --goodput``
reduces a router log to request percentiles, per-replica MTTR, and
fleet availability in one pass (the ``fleet`` block).

`router.py` at the repo root is the CLI driver (subprocess replicas,
per-replica chaos plans for drills); `tests/test_router.py` pins the
in-process canaries and the schema; the cross-process fleet chaos
drill rides the slow tier.
"""

from __future__ import annotations

import http.client
import json
import os
import random
import signal
import subprocess
import time
import urllib.request
from collections import deque

import numpy as np

from shallowspeed_tpu.elastic import (RestartPolicy, classify_exit,
                                      read_heartbeat_status,
                                      write_heartbeat)
from shallowspeed_tpu.serving.cache import chunk_hashes
from shallowspeed_tpu.telemetry.monitor import parse_slos
from shallowspeed_tpu.telemetry.tracing import new_span_id, new_trace_id


class FleetOverloaded(RuntimeError):
    """Fleet-edge backpressure: `Router.submit` rejects because every
    breaker is open / every replica is down or draining, or the
    router's pending queue exceeds its budget. `retry_after` is the
    caller's hint (seconds) — the earliest breaker reopen / respawn,
    or one poll interval for queue pressure."""

    def __init__(self, msg: str, retry_after: float):
        super().__init__(f"{msg} (retry after ~{retry_after:.1f}s)")
        self.retry_after = float(retry_after)


class CircuitBreaker:
    """Per-replica circuit breaker: closed → (threshold consecutive
    failures, or a force-open on observed death) → open for a
    jittered, doubling cooldown → half-open admitting ONE probe →
    closed on probe success / re-open on probe failure. Transitions
    invoke `on_transition(state, now)` so the router can stamp them."""

    def __init__(self, threshold: int = 3, cooldown: float = 1.0,
                 cooldown_max: float = 30.0, jitter: float = 0.25,
                 seed: int = 0, on_transition=None):
        self.threshold = int(threshold)
        self.cooldown = float(cooldown)
        self.cooldown_max = float(cooldown_max)
        self.jitter = float(jitter)
        self.on_transition = on_transition
        self._rng = random.Random(seed)
        self.state = "closed"
        self.failures = 0
        self.trips = 0
        self._cool = self.cooldown
        self._open_until = 0.0
        self._probe_out = False

    def _set(self, state: str, now: float) -> None:
        if state == self.state:
            return
        self.state = state
        if self.on_transition is not None:
            self.on_transition(state, now)

    def _open(self, now: float) -> None:
        # jittered cooldown, doubling per consecutive trip: a fleet of
        # routers probing one recovering replica must not thunder
        delay = self._cool * (1.0 + self.jitter * self._rng.random())
        self._cool = min(self._cool * 2.0, self.cooldown_max)
        self._open_until = now + delay
        self._probe_out = False
        self.trips += 1
        self._set("open", now)

    def force_open(self, now: float) -> None:
        """Observed replica death: no need to wait for the failure
        count — stop routing there until a probe succeeds."""
        self.failures = 0
        self._open(now)

    def allow(self, now: float) -> bool:
        """May a call go to this replica now? Open→half-open happens
        here (cooldown elapsed); half-open admits one probe at a
        time. The PROGRESS POLL is the probe in practice — dispatch
        itself waits for `closed`."""
        if self.state == "closed":
            return True
        if self.state == "open":
            if now < self._open_until:
                return False
            self._set("half_open", now)
        if self._probe_out:
            return False
        self._probe_out = True
        return True

    def note_success(self, now: float) -> None:
        self.failures = 0
        if self.state == "half_open":
            self._cool = self.cooldown
            self._probe_out = False
            self._set("closed", now)

    def note_failure(self, now: float) -> None:
        self.failures += 1
        if self.state == "half_open" \
                or (self.state == "closed"
                    and self.failures >= self.threshold):
            self.failures = 0
            self._open(now)

    def retry_after(self, now: float) -> float:
        return max(0.0, self._open_until - now)


# ------------------------------------------------- replica-side gateway


def _submit_typed(engine, payload: dict) -> dict:
    """Translate one `ServingEngine.submit` into the typed dict reply
    the router understands ({"ok"} / {"ok": False, "error",
    ["retry_after"]}). Shared by the HTTP gateway and the in-process
    handle — the in-process canary stays faithful to the wire shape
    because both speak through this one function."""
    from shallowspeed_tpu.serving.engine import EngineDraining

    rid = str(payload.get("id"))
    try:
        att = payload.get("attempt")
        engine.submit(np.asarray(payload["prompt"], np.int32),
                      int(payload["max_new"]),
                      temperature=float(payload.get("temperature",
                                                    0.0)),
                      seed=int(payload.get("seed", 0)), rid=rid,
                      generated=payload.get("generated") or (),
                      # schema v11 trace context: minted by the
                      # router, riding the POST /submit body — a
                      # failover re-dispatch carries the SAME trace
                      # with an incremented attempt
                      trace=payload.get("trace"),
                      parent=payload.get("parent"),
                      attempt=int(att) if isinstance(att, int)
                      and not isinstance(att, bool) else 0)
    except EngineDraining:
        return {"ok": False, "error": "EngineDraining",
                "retry_after": 1.0}
    except (KeyError, TypeError, ValueError) as e:
        return {"ok": False, "error": f"{type(e).__name__}: {e}"}
    return {"ok": True, "id": rid}


def _snapshot_requests(engine, rids) -> dict[str, dict]:
    """Per-request {"status", "tokens"} snapshots out of the engine —
    the one shape `Router._fold_progress` consumes, shared by the
    gateway's publish and the in-process handle's progress."""
    out = {}
    for rid in rids:
        if rid in engine.results:
            out[rid] = {"status": "done",
                        "tokens": [int(t) for t
                                   in engine.results[rid]]}
        else:
            try:
                p = engine.poll(rid)
                out[rid] = {"status": p["status"],
                            "tokens": [int(t) for t
                                       in p["tokens"]]}
            except KeyError:
                continue      # still in an inbox, or rejected
    return out


class RequestGateway:
    """The replica-side ingestion surface: a thread-safe inbox the
    serve loop pumps into its `ServingEngine`, plus published
    per-request snapshots the router polls. Grafted onto the replica's
    monitor endpoint by `StatusServer(extra=...)`:

    - ``POST /submit``  -> `submit_request(payload)`: queue one request
      ({"id", "prompt": [ids], "max_new", "temperature", "seed",
      "generated": [resume prefix]}); typed dict rejections
      ({"ok": False, "error": "EngineDraining"|"EngineOverloaded",
      "retry_after": s}) instead of silent queue growth.
    - ``GET /requests`` -> `poll_requests()`: every known request's
      {"status": queued|running|done|rejected, "tokens": so-far}.
    - ``POST /drain``   -> `drain_request(...)`: graceful drain — the
      serve loop stops admission (`engine.drain()`), finishes
      in-flight work, deregisters, and exits 0.

    HTTP handler threads only touch the inbox and the published
    snapshots under the lock; `pump()`/`publish()` run on the engine's
    own thread — the engine itself is never shared across threads.

    Terminal (done/rejected) snapshots are retained up to
    `done_cap` and then evicted FIFO — a long-lived replica must not
    grow one full token list per request it ever served, and the
    router re-reads a result within a poll interval of completion, so
    thousands of retained terminals are already generous."""

    def __init__(self, max_queue: int = 256, done_cap: int = 4096,
                 clock=time.time):
        import threading

        self.max_queue = int(max_queue)
        self.done_cap = int(done_cap)
        self.clock = clock
        self._lock = threading.Lock()
        self._inbox: deque = deque()
        self._known: list[str] = []
        self.published: dict[str, dict] = {}
        self.drain_requested = False

    # ---- HTTP-thread side (duck-typed into StatusServer) -----------

    def submit_request(self, payload: dict) -> dict:
        rid = str(payload.get("id"))
        with self._lock:
            if self.drain_requested:
                return {"ok": False, "error": "EngineDraining",
                        "retry_after": 1.0}
            if rid in self.published and \
                    self.published[rid]["status"] != "rejected":
                return {"ok": False,
                        "error": f"ValueError: duplicate id {rid!r}"}
            # inbox entries are already published as "queued", so the
            # published states alone are the backlog
            backlog = sum(1 for p in self.published.values()
                          if p["status"] in ("queued", "running"))
            if backlog >= self.max_queue:
                return {"ok": False, "error": "EngineOverloaded",
                        "retry_after": 0.5}
            self._inbox.append(dict(payload))
            self._known.append(rid)
            self.published[rid] = {"status": "queued", "tokens": []}
        return {"ok": True, "id": rid}

    def poll_requests(self, payload: dict | None = None) -> dict:
        with self._lock:
            return {"requests": {rid: dict(rec) for rid, rec
                                 in self.published.items()},
                    "draining": self.drain_requested}

    def drain_request(self, payload: dict | None = None) -> dict:
        with self._lock:
            self.drain_requested = True
            backlog = sum(1 for p in self.published.values()
                          if p["status"] in ("queued", "running"))
        return {"draining": True, "pending": backlog}

    def idle(self) -> bool:
        with self._lock:
            return not self._inbox

    # ---- engine-thread side ----------------------------------------

    def pump(self, engine) -> int:
        """Move inbox submissions into the engine (engine thread
        only). Bad requests publish as `rejected` with the error —
        one malformed request must not kill the replica."""
        n = 0
        while True:
            with self._lock:
                if not self._inbox:
                    return n
                payload = self._inbox.popleft()
            resp = _submit_typed(engine, payload)
            if resp.get("ok"):
                n += 1
            else:
                with self._lock:
                    self.published[str(payload.get("id"))] = {
                        "status": "rejected",
                        "error": resp["error"], "tokens": []}

    def publish(self, engine) -> None:
        """Snapshot every known NON-terminal request's state out of
        the engine (engine thread only) for the HTTP pollers; evict
        the oldest terminal snapshots beyond `done_cap`."""
        with self._lock:
            terminal = {rid for rid, rec in self.published.items()
                        if rec["status"] in ("done", "rejected")}
            known = [rid for rid in self._known
                     if rid not in terminal]
            self._known = known     # terminals never re-snapshot
        snap = _snapshot_requests(engine, known)
        with self._lock:
            for rid, rec in snap.items():
                self.published[rid] = rec
            fin = [rid for rid, rec in self.published.items()
                   if rec["status"] in ("done", "rejected")]
            for rid in fin[:max(0, len(fin) - self.done_cap)]:
                del self.published[rid]


# ------------------------------------------------------ replica handles


class InProcessReplica:
    """In-process replica handle: a real `ServingEngine` behind the
    same surface `ReplicaProc` exposes over HTTP — the router logic is
    identical, which is what makes the default-tier failover canary
    and the bench fleet sweep faithful to the cross-process drill.
    `kill()` simulates SIGKILL (the engine object — all cache state —
    is discarded; calls raise ConnectionError until `respawn()`)."""

    def __init__(self, name: str, engine_factory, clock=time.time):
        self.name = name
        self._factory = engine_factory
        self.clock = clock
        self.engine = engine_factory(name)
        self.proc_alive = True
        self._fail_class: str | None = None
        self._known: list[str] = []

    # lifecycle ------------------------------------------------------

    def check(self, now: float) -> str | None:
        """None while healthy; a FAIL_CLASSES entry once dead;
        "clean" after a completed drain exit."""
        if not self.proc_alive:
            return self._fail_class
        if self.engine.draining and self.engine.pending() == 0:
            self.proc_alive = False
            self._fail_class = "clean"
            return "clean"
        return None

    def kill(self, fail_class: str = "crash") -> None:
        self.proc_alive = False
        self._fail_class = fail_class
        self.engine = None          # cache state dies with the process

    def respawn(self) -> None:
        self.engine = self._factory(self.name)
        self.proc_alive = True
        self._fail_class = None
        self._known = []

    def ready(self, now: float) -> bool:
        return self.proc_alive

    def stop(self) -> None:
        self.proc_alive = False

    def pump(self) -> bool:
        if self.proc_alive and self.engine.pending():
            return self.engine.step()
        return False

    # request surface (ConnectionError == the process is gone) -------

    def _engine(self):
        if not self.proc_alive or self.engine is None:
            raise ConnectionError(f"replica {self.name} is down")
        return self.engine

    def submit(self, payload: dict) -> dict:
        eng = self._engine()
        resp = _submit_typed(eng, payload)
        if resp.get("ok"):
            self._known.append(str(payload.get("id")))
        return resp

    def progress(self) -> dict:
        eng = self._engine()
        out = _snapshot_requests(eng, self._known)
        # bounded history, like the gateway's done_cap: keep the
        # most recent completions only (the router consumes a result
        # within one poll interval)
        if len(self._known) > 4096:
            done = [r for r in self._known if r in eng.results]
            drop = set(done[:len(self._known) - 4096])
            self._known = [r for r in self._known if r not in drop]
        return {"requests": out, "draining": eng.draining}

    def drain(self) -> dict:
        eng = self._engine()
        done = eng.drain()
        return {"draining": True, "pending": eng.pending(),
                "done": done}

    def telemetry(self) -> dict:
        if not self.proc_alive or self.engine is None:
            return {}
        eng = self.engine
        out = {"queue_depth": len(eng.queue),
               "active_slots": sum(1 for s in eng.slots
                                   if s is not None),
               "free_blocks": eng.alloc.n_free}
        # v15 capacity plane: the live admission-headroom estimate —
        # same fields the subprocess path reads off the fleet
        # collector's serving view
        out.update(eng.headroom())
        return out


class ReplicaProc:
    """Subprocess replica handle: one `serve.py --serve` child with
    its own monitor+gateway endpoint, heartbeat file, and metrics
    JSONL. The child self-registers its endpoint URL at the router's
    fleet collector (``--fleet-register``), which is how the router
    learns where to submit — no stdout parsing, no fixed ports.

    `check()` implements elastic.py's failure taxonomy for a serving
    child: nonzero exit → crash/corrupt_ckpt (`classify_exit`), a
    heartbeat whose STATUS reads "dead ..." → numeric (killed), a
    heartbeat stale past `hang_timeout` → hang (killed). Exit 0 is
    "clean" — the drain path."""

    def __init__(self, name: str, argv: list[str], collector, *,
                 heartbeat_file: str | None = None,
                 hang_timeout: float | None = None,
                 startup_timeout: float | None = None,
                 term_grace: float = 5.0, timeout: float = 5.0,
                 stdout_path: str | None = None, clock=time.time):
        self.name = name
        self.argv = list(argv)
        self.collector = collector
        self.heartbeat_file = heartbeat_file
        self.hang_timeout = hang_timeout
        # a child can wedge BEFORE its first registration (frozen in
        # jax import, or its --fleet-register POST never landing) —
        # the post-registration staleness clock never arms for it, so
        # a separate, much more generous startup deadline classes it
        # as a hang instead of leaving it "warming" forever while
        # submit() counts it as routable capacity
        self.startup_timeout = (
            float(startup_timeout) if startup_timeout is not None
            else (max(60.0, 3.0 * hang_timeout)
                  if hang_timeout is not None else None))
        self.term_grace = float(term_grace)
        self.timeout = float(timeout)
        self.stdout_path = stdout_path
        self.clock = clock
        self.proc = None
        self._hb_seen = 0.0
        self._beating = False        # first registration seen yet?
        self._stale_url = None       # pre-respawn URL, not the child's
        self._spawn()

    # lifecycle ------------------------------------------------------

    def _spawn(self) -> None:
        if self.heartbeat_file:
            # fresh liveness clock + fresh status per attempt (the
            # Supervisor._run_once contract: a leftover 'dead' must
            # not kill every respawn within one poll)
            try:
                write_heartbeat(self.heartbeat_file, "ok")
            except OSError:
                pass
        out = None
        if self.stdout_path:
            # per-replica console log: N children's result lines must
            # not interleave with the router's own stdout
            out = open(self.stdout_path, "ab")
        try:
            self.proc = subprocess.Popen(
                self.argv, stdout=out, stderr=out,
                stdin=subprocess.DEVNULL)
        finally:
            if out is not None:
                out.close()           # the child holds its own fd
        self._hb_seen = time.time()
        self._beating = False

    def _terminate(self) -> None:
        """SIGTERM with grace (the child's handler flushes its metrics
        tail), then SIGKILL — the Supervisor kill path."""
        if self.proc is None or self.proc.poll() is not None:
            return
        if self.term_grace > 0:
            self.proc.send_signal(signal.SIGTERM)
            try:
                self.proc.wait(timeout=self.term_grace)
                return
            except subprocess.TimeoutExpired:
                pass
        self.proc.send_signal(signal.SIGKILL)
        self.proc.wait()

    def check(self, now: float) -> str | None:
        code = self.proc.poll()
        if code is not None:
            return classify_exit(code) or "clean"
        if self.heartbeat_file:
            status = read_heartbeat_status(self.heartbeat_file)
            if status.startswith("dead"):
                self._terminate()
                return "numeric"
            if not self._beating:
                # the staleness clock starts at the replica's (re-)
                # registration: a child spending seconds in jax import
                # before its first beat is warming up, not hung — a
                # stale-at-spawn kill would hang-loop every replica
                # through its own startup until the budget died
                if self.ready(now):
                    self._beating = True
                    self._hb_seen = time.time()
                elif self.startup_timeout is not None \
                        and time.time() - self._hb_seen \
                        > self.startup_timeout:
                    # never registered within the (generous) startup
                    # deadline: wedged before first beat
                    self._terminate()
                    return "hang"
            elif self.hang_timeout is not None:
                try:
                    self._hb_seen = max(
                        self._hb_seen,
                        os.path.getmtime(self.heartbeat_file))
                except OSError:
                    pass
                if time.time() - self._hb_seen > self.hang_timeout:
                    self._terminate()
                    return "hang"
        return None

    def kill(self, fail_class: str = "crash") -> None:
        self._terminate()

    def respawn(self) -> None:
        # the collector still holds the DEAD process's URL until the
        # new child re-registers (by name) — remember it, so ready()
        # waits for the fresh endpoint instead of declaring the
        # respawn done against a socket nobody listens on
        self._stale_url = self.url
        self._spawn()

    def ready(self, now: float) -> bool:
        """Respawn completes when the child is running AND has
        (re-)registered its own endpoint at the collector."""
        url = self.url
        return (self.proc.poll() is None and url is not None
                and url != self._stale_url)

    def stop(self) -> None:
        self._terminate()

    def pump(self) -> bool:
        return False                # the child pumps itself

    # request surface ------------------------------------------------

    @property
    def url(self) -> str | None:
        rep = self._fleet_rep()
        return rep.url if rep is not None else None

    def _fleet_rep(self):
        if self.collector is None:
            return None
        for rep in self.collector.replicas:
            if rep.name == self.name and rep.url:
                return rep
        return None

    def _call(self, endpoint: str, payload=None):
        url = self.url
        if url is None:
            raise ConnectionError(f"replica {self.name} has not "
                                  f"registered an endpoint yet")
        req = urllib.request.Request(
            url + endpoint,
            data=(json.dumps(payload).encode()
                  if payload is not None else None),
            headers={"Content-Type": "application/json"}
            if payload is not None else {})
        try:
            with urllib.request.urlopen(req,
                                        timeout=self.timeout) as r:
                return json.loads(r.read())
        except (http.client.HTTPException,
                json.JSONDecodeError) as e:
            # a replica dying MID-RESPONSE raises IncompleteRead (an
            # HTTPException, not an OSError) or JSONDecodeError on
            # the truncated body — to the router both mean exactly
            # what a refused connection means: the replica is gone
            raise ConnectionError(
                f"replica {self.name}: "
                f"{type(e).__name__}: {e}") from e

    def submit(self, payload: dict) -> dict:
        return self._call("/submit", payload)

    def progress(self) -> dict:
        return self._call("/requests")

    def drain(self) -> dict:
        return self._call("/drain", {})

    def telemetry(self) -> dict:
        """Admission inputs out of the FleetCollector's last poll of
        this replica — queue depth / active slots / free blocks from
        the serving block, ttft p50 from the sketch quantiles. The
        router consumes the collector; it never re-polls."""
        rep = self._fleet_rep()
        if rep is None:
            return {}
        summary = rep.summary()
        out = dict(summary.get("serving") or {})
        q = (summary.get("quantiles") or {}).get("ttft_ms")
        if q and q.get("p50") is not None:
            out["ttft_p50_ms"] = q["p50"]
        return out


# --------------------------------------------------------------- router


class _RouterReq:
    __slots__ = ("rid", "prompt", "max_new", "temp", "seed",
                 "submit_t", "deadline", "tokens", "replica",
                 "dispatch_t", "last_progress_t", "first_tok_t",
                 "failovers", "failover_from", "failover_reason",
                 "exclude", "trace", "span", "attempt", "fp")

    def __init__(self, rid, prompt, max_new, temp, seed, now,
                 deadline):
        self.rid = rid
        self.prompt = np.asarray(prompt, np.int32).reshape(-1)
        self.max_new = int(max_new)
        self.temp = float(temp)
        self.seed = int(seed)
        self.submit_t = now
        self.deadline = deadline          # absolute wall, or None
        self.tokens: list[int] = []       # received so far (ordered)
        self.replica: str | None = None   # current assignment
        self.dispatch_t = None
        self.last_progress_t = now
        self.first_tok_t = None
        self.failovers = 0
        self.failover_from: str | None = None
        self.failover_reason: str | None = None
        self.exclude: str | None = None   # skip on the next dispatch
        # trace context (schema v11): one trace id for the request's
        # whole fleet journey, a root span for the router's custody,
        # and the 0-based cross-engine dispatch attempt counter the
        # per-replica lifecycle events echo back
        self.trace = new_trace_id()
        self.span = new_span_id()
        self.attempt = -1                 # first dispatch -> 0
        # sticky routing: chained hashes of the prompt's leading
        # aligned chunks (the same chunk identity the engines' prefix
        # index keys on) — empty when sticky is off or the prompt is
        # shorter than one chunk
        self.fp: tuple = ()


class Router:
    """The fleet front-end (module docstring). `spawn(name)` returns a
    replica handle (`ReplicaProc` in production, `InProcessReplica`
    in-process); the router owns every handle's lifecycle from then
    on. Drive it with `step()` from an event loop, or `run()` to
    drain a submitted batch."""

    def __init__(self, spawn, n_replicas: int = 2, *, collector=None,
                 metrics=None, slos: str = "", slo_kw: dict | None = None,
                 clock=time.time, seed: int = 0,
                 queue_budget: int = 256,
                 request_timeout: float | None = 30.0,
                 default_deadline_s: float | None = None,
                 progress_interval: float = 0.0,
                 breaker_kw: dict | None = None,
                 policy_kw: dict | None = None,
                 autoscale: bool = False, min_replicas: int = 1,
                 max_replicas: int = 4, scale_hold_s: float = 5.0,
                 idle_drain_s: float = 30.0,
                 scale_cooldown_s: float = 10.0,
                 sticky: bool = True, sticky_block: int = 16,
                 sticky_bonus: float = 0.5, sticky_cap: float = 1.5,
                 sticky_history: int = 2048):
        self.spawn = spawn
        self.collector = collector
        self.metrics = metrics
        self.clock = clock
        self.queue_budget = int(queue_budget)
        self.request_timeout = request_timeout
        self.default_deadline_s = default_deadline_s
        self.progress_interval = float(progress_interval)
        self.breaker_kw = dict(breaker_kw or {})
        self.policy_kw = dict(policy_kw or {})
        self.autoscale = bool(autoscale)
        self.min_replicas = int(min_replicas)
        self.max_replicas = int(max_replicas)
        self.scale_hold_s = float(scale_hold_s)
        self.idle_drain_s = float(idle_drain_s)
        self.scale_cooldown_s = float(scale_cooldown_s)
        # sticky prefix-affinity routing (round 19): the router
        # fingerprints each prompt's leading aligned chunks
        # (`cache.chunk_hashes`, the SAME chunk identity the replicas'
        # prefix index keys on) and remembers, per replica, which
        # chunks its own dispatch history sent where. At rank time a
        # replica earns a bonus of `sticky_bonus` per matched leading
        # chunk, CAPPED at `sticky_cap` — one queued request outscores
        # the cap, so load/burn signals always override locality and a
        # popular prefix cannot create a hotspot. Pure dispatch-side
        # state: failover re-dispatch (`generated=`) stays correct
        # because the fallback replica simply misses its cache.
        self.sticky = bool(sticky)
        self.sticky_block = int(sticky_block)
        self.sticky_bonus = float(sticky_bonus)
        self.sticky_cap = float(sticky_cap)
        self.sticky_history = int(sticky_history)
        self._affinity: dict[str, dict[bytes, None]] = {}
        self._rng = random.Random(seed)
        # fleet-edge SLO rules: ttft fed from the router's own
        # submit→first-token observations, availability from replica
        # detection→ready downtime — monitor.SloRule's dual-window
        # burn evaluation IS the autoscale signal
        self.rules = parse_slos(slos, **(slo_kw or {}))
        self.pending: deque[_RouterReq] = deque()
        self.inflight: dict[str, _RouterReq] = {}
        self.results: dict[str, np.ndarray] = {}
        self.records: list[dict] = []
        self.events: list[dict] = []
        self.counters = {"submitted": 0, "finished": 0, "failed": 0,
                         "routes": 0, "failovers": 0, "rejected": 0,
                         "breaker_trips": 0, "respawns": 0,
                         "scale_ups": 0, "scale_downs": 0}
        self._replicas: dict[str, dict] = {}
        self._breakers: dict[str, CircuitBreaker] = {}
        self._policies: dict[str, RestartPolicy] = {}
        self._next_idx = 0
        self._crit_since: float | None = None
        self._idle_since: float | None = None
        self._last_scale_t = -1e18
        self._last_progress_poll = -1e18
        for _ in range(int(n_replicas)):
            self._add_replica(self.clock())

    # ------------------------------------------------------ membership

    def _add_replica(self, now: float) -> str:
        name = f"r{self._next_idx}"
        self._next_idx += 1
        handle = self.spawn(name)
        self._replicas[name] = {
            "handle": handle, "alive": True, "warming": True,
            "draining": False, "retired": False,
            "down_since": None, "respawn_at": None,
            "respawning": False, "fail_class": None,
        }
        self._breakers[name] = CircuitBreaker(
            seed=self._rng.randrange(1 << 30),
            on_transition=lambda st, t, n=name:
                self._on_breaker(n, st, t),
            **self.breaker_kw)
        self._policies[name] = RestartPolicy(
            seed=self._rng.randrange(1 << 30), **self.policy_kw)
        return name

    def _on_breaker(self, name: str, state: str, now: float) -> None:
        if state == "open":
            self.counters["breaker_trips"] += 1
        self._emit("ledger", kind="breaker", replica=name, state=state)

    def _emit(self, event: str, **fields) -> None:
        rec = {"event": event, **fields}
        self.events.append(rec)
        if self.metrics is not None:
            self.metrics.log(**rec)

    def replica_names(self, live_only: bool = False) -> list[str]:
        return [n for n, e in self._replicas.items()
                if not e["retired"]
                and (not live_only or (e["alive"] and not e["draining"]))]

    # --------------------------------------------------------- clients

    def submit(self, prompt, max_new: int, temperature: float = 0.0,
               seed: int = 0, rid: str | None = None,
               deadline_s: float | None = None) -> str:
        """Queue one request with the fleet. Raises the typed
        `FleetOverloaded` (with retry_after) when the fleet cannot
        accept work right now — every breaker open / replica down or
        draining, or the router queue past its budget."""
        now = self.clock()
        rid = rid if rid is not None else f"q{self.counters['submitted']}"
        if rid in self.inflight or rid in self.results \
                or any(r.rid == rid for r in self.pending):
            raise ValueError(f"duplicate request id {rid!r}")
        # warming replicas count as routable capacity (they are about
        # to register) — work queues for them instead of rejecting
        routable = [n for n in self.replica_names(live_only=True)
                    if self._breakers[n].state != "open"]
        if not routable:
            self.counters["rejected"] += 1
            raise FleetOverloaded(
                "no routable replica (breakers open or replicas "
                "down/draining)", self._min_retry_after(now))
        if len(self.pending) >= self.queue_budget:
            self.counters["rejected"] += 1
            raise FleetOverloaded(
                f"router queue at budget ({self.queue_budget})", 1.0)
        dl = deadline_s if deadline_s is not None \
            else self.default_deadline_s
        req = _RouterReq(rid, prompt, max_new, temperature, seed, now,
                         now + dl if dl is not None else None)
        if self.sticky:
            req.fp = tuple(chunk_hashes(req.prompt, self.sticky_block))
        self.pending.append(req)
        self.counters["submitted"] += 1
        return rid

    def _min_retry_after(self, now: float) -> float:
        waits = [self._breakers[n].retry_after(now)
                 for n, e in self._replicas.items()
                 if not e["retired"]
                 and self._breakers[n].state == "open"]
        waits += [max(0.0, e["respawn_at"] - now)
                  for e in self._replicas.values()
                  if e["respawn_at"] is not None and not e["alive"]]
        return min(waits) if waits else 1.0

    def unfinished(self) -> int:
        return len(self.pending) + len(self.inflight)

    def fail_unfinished(self, reason: str) -> int:
        """Terminally fail every pending and in-flight request (a
        `records` entry with status "failed" each) — the driver's
        last act when the fleet dies for good, so no submitted id
        ever vanishes without a result or error record."""
        now = self.clock()
        n = 0
        while self.pending:
            self._finalize(self.pending.popleft(), now,
                           status="failed", error=reason)
            n += 1
        for req in list(self.inflight.values()):
            self._finalize(req, now, status="failed", error=reason)
            n += 1
        return n

    # ------------------------------------------------------------ step

    def step(self, now: float | None = None) -> bool:
        now = self.clock() if now is None else now
        did = False
        self._supervise(now)
        for name, entry in self._replicas.items():
            if entry["alive"] and not entry["retired"]:
                did = entry["handle"].pump() or did
        did = self._poll_progress(now) or did
        self._check_timeouts(now)
        did = self._dispatch(now) or did
        self._drain_progress(now)
        self._evaluate_rules(now)
        self._autoscale(now)
        return did

    def run(self, max_wall: float = 600.0, poll: float = 0.02) -> dict:
        """Drain: step until every submitted request finished or
        failed (bounded by `max_wall` REAL seconds)."""
        t0 = time.monotonic()
        while self.unfinished():
            if time.monotonic() - t0 > max_wall:
                raise RuntimeError(
                    f"router did not drain within {max_wall}s "
                    f"(pending={len(self.pending)}, "
                    f"inflight={len(self.inflight)})")
            if not self.step():
                time.sleep(poll)
        return dict(self.results)

    def shutdown(self) -> None:
        """Stop every replica (SIGTERM/SIGKILL for processes). The
        router object is done after this."""
        for entry in self._replicas.values():
            try:
                entry["handle"].stop()
            except Exception:
                pass

    # ------------------------------------------------------ supervision

    def _supervise(self, now: float) -> None:
        for name, entry in list(self._replicas.items()):
            if entry["retired"]:
                continue
            h = entry["handle"]
            if entry["alive"]:
                if entry["warming"] and h.ready(now):
                    entry["warming"] = False
                try:
                    fail = h.check(now)
                except Exception:
                    fail = "crash"
                if fail == "clean":
                    if entry["draining"]:
                        self._finish_drain(name, now)
                    else:
                        # a serving replica has no clean exit outside
                        # a drain — treat it as a crash
                        self._on_replica_down(name, "crash", now)
                elif fail is not None:
                    self._on_replica_down(name, fail, now)
            elif entry["respawning"]:
                if h.ready(now):
                    entry["alive"] = True
                    entry["respawning"] = False
                    entry["warming"] = False
                    self.counters["respawns"] += 1
                    downtime = now - entry["down_since"]
                    self._note_downtime(downtime, now)
                    self._emit("ledger", kind="restart_downtime",
                               seconds=round(downtime, 3),
                               fail_class=entry["fail_class"],
                               replica=name)
                    entry["down_since"] = None
            elif entry["respawn_at"] is not None \
                    and now >= entry["respawn_at"]:
                try:
                    h.respawn()
                    entry["respawning"] = True
                except Exception:
                    entry["respawn_at"] = now + 1.0

    def _on_replica_down(self, name: str, fail_class: str,
                         now: float) -> None:
        entry = self._replicas[name]
        entry["alive"] = False
        entry["down_since"] = now
        entry["fail_class"] = fail_class
        self._breakers[name].force_open(now)
        # a dead replica's prefix cache died with it — its affinity
        # history must not attract the respawned (cold) successor
        self._affinity.pop(name, None)
        # in-flight work fails over: back to the FRONT of the queue,
        # carrying every token already received — the re-dispatch
        # re-prefills prompt + prefix on another replica and the
        # stream continues token-identically (seeded sampling)
        moved = [r for r in self.inflight.values()
                 if r.replica == name]
        for req in moved:
            req.failover_from = name
            req.failover_reason = "death"
            req.exclude = name
            req.replica = None
            del self.inflight[req.rid]
            self.pending.appendleft(req)
        if entry["draining"]:
            # it died mid-drain; what it had is failing over anyway —
            # complete the scale-down instead of respawning
            self._finish_drain(name, now)
            return
        delay = self._policies[name].next_restart(fail_class)
        if delay is None:
            entry["retired"] = True
            self._emit("ledger", kind="replica_retired", replica=name,
                       fail_class=fail_class)
        else:
            entry["respawn_at"] = now + delay

    # ------------------------------------------------------- progress

    def _poll_progress(self, now: float) -> bool:
        if self.progress_interval and \
                now - self._last_progress_poll < self.progress_interval:
            return False
        self._last_progress_poll = now
        did = False
        for name, entry in self._replicas.items():
            if not entry["alive"] or entry["retired"] \
                    or entry["warming"]:
                # a warming replica (spawned, not yet registered) has
                # no endpoint to poll — failing its breaker for that
                # would reject traffic the fleet is about to gain
                continue
            br = self._breakers[name]
            # a non-closed breaker gates the poll through allow():
            # this IS the jittered half-open probe — one successful
            # poll re-closes the breaker and traffic returns
            if br.state != "closed" and not br.allow(now):
                continue
            h = entry["handle"]
            try:
                prog = h.progress()
            except (OSError, ConnectionError):
                br.note_failure(now)
                continue
            br.note_success(now)
            did = self._fold_progress(name, prog.get("requests") or {},
                                      now) or did
        return did

    def _fold_progress(self, name: str, snap: dict,
                       now: float) -> bool:
        did = False
        for rid, rec in snap.items():
            req = self.inflight.get(rid)
            if req is None or req.replica != name:
                continue            # stale duplicate from a failover
            status = rec.get("status")
            toks = rec.get("tokens") or []
            if status == "rejected":
                self._finalize(req, now, status="rejected",
                               error=rec.get("error"))
                continue
            if len(toks) > len(req.tokens):
                req.tokens = [int(t) for t in toks]
                req.last_progress_t = now
                did = True
                if req.first_tok_t is None:
                    req.first_tok_t = now
                    ttft_ms = (now - req.submit_t) * 1e3
                    for rule in self.rules:
                        if rule.sketch == "ttft_ms":
                            rule.record(ttft_ms, now)
            if status == "done" and len(req.tokens) >= req.max_new:
                self._finalize(req, now, status="done")
        return did

    def _finalize(self, req: _RouterReq, now: float, status: str,
                  error: str | None = None) -> None:
        self.inflight.pop(req.rid, None)
        # e2e from a FRESH clock read, not the step-loop `now`: the
        # request record's log stamp is the stitcher's finish mark,
        # and a stale `now` (captured before this step's polls or an
        # in-process engine's compile) would make the record's e2e
        # disagree with its own stamp by that lag — which the
        # waterfall would book as rq_unexplained
        rec = {"id": req.rid, "status": status,
               "replica": req.replica, "failovers": req.failovers,
               "trace": req.trace, "span": req.span,
               "tokens_in": int(req.prompt.shape[0]),
               "tokens_out": len(req.tokens),
               "e2e_ms": round(
                   (self.clock() - req.submit_t) * 1e3, 3)}
        if req.first_tok_t is not None:
            rec["ttft_ms"] = round(
                (req.first_tok_t - req.submit_t) * 1e3, 3)
        if error:
            rec["error"] = str(error)
        self.records.append(rec)
        if status == "done":
            self.results[req.rid] = np.asarray(req.tokens, np.int32)
            self.counters["finished"] += 1
            if self.metrics is not None and "ttft_ms" in rec:
                # the fleet-edge request record (schema v6 shape +
                # v10 replica/failovers fields): --goodput over the
                # ROUTER log alone yields user-felt percentiles
                self.metrics.log(event="request", **{
                    k: v for k, v in rec.items() if k != "status"})
        else:
            self.counters["failed"] += 1
            self._emit("ledger", kind=f"request_{status}", count=1,
                       replica=req.replica or "?")

    def _check_timeouts(self, now: float) -> None:
        for req in list(self.inflight.values()):
            if req.deadline is not None and now > req.deadline:
                self._finalize(req, now, status="deadline_exceeded")
                continue
            if self.request_timeout is not None \
                    and now - req.last_progress_t > self.request_timeout:
                # stalled: penalize the replica, fail the request over
                self._breakers[req.replica].note_failure(now)
                req.failover_from = req.replica
                req.failover_reason = "timeout"
                req.exclude = req.replica
                req.replica = None
                req.last_progress_t = now
                del self.inflight[req.rid]
                self.pending.appendleft(req)
        for req in list(self.pending):
            if req.deadline is not None and now > req.deadline:
                self.pending.remove(req)
                self._finalize(req, now, status="deadline_exceeded")

    # -------------------------------------------------------- dispatch

    def _score(self, name: str, now: float) -> float:
        """Admission weight: the router's own in-flight count plus the
        replica's polled queue/slot pressure, minus free headroom,
        plus a tail-latency penalty when its ttft p50 is elevated —
        the /status.json-weighted dispatch the FleetCollector feeds."""
        entry = self._replicas[name]
        t = {}
        try:
            t = entry["handle"].telemetry() or {}
        except Exception:
            pass
        s = float(sum(1 for r in self.inflight.values()
                      if r.replica == name))
        s += float(t.get("queue_depth") or 0)
        s += 0.5 * float(t.get("active_slots") or 0)
        fb = t.get("free_blocks")
        if isinstance(fb, (int, float)):
            s -= 0.001 * min(float(fb), 1000.0)
        # v15 capacity plane: NEGATIVE admission headroom means the
        # replica's accepted max-token budgets already overcommit its
        # block pool — placing more work there buys evictions, not
        # throughput. One overcommitted block outweighs one queued
        # request so a near-OOM replica sheds load BEFORE it evicts;
        # capped like the ttft penalty so a deeply-overcommitted
        # replica still ranks (it may be the only one alive).
        hb = t.get("headroom_blocks")
        if isinstance(hb, (int, float)) and hb < 0:
            s += min(-float(hb), 20.0)
        ttft = t.get("ttft_p50_ms")
        if isinstance(ttft, (int, float)) and ttft > 0:
            s += min(float(ttft) / 1e3, 10.0)    # seconds of p50 ttft
        return s

    def _affinity_bonus(self, name: str, req) -> float:
        """Sticky prefix-affinity bonus: `sticky_bonus` per LEADING
        fingerprint chunk this replica has already served (contiguous
        from the front — a mid-prompt match is useless to the prefix
        cache), capped at `sticky_cap` so one unit of queue pressure
        always outranks locality."""
        if not req.fp:
            return 0.0
        seen = self._affinity.get(name)
        if not seen:
            return 0.0
        n = 0
        for h in req.fp:
            if h not in seen:
                break
            n += 1
        return min(self.sticky_cap, self.sticky_bonus * n)

    def _note_affinity(self, name: str, req) -> None:
        """Record the dispatched prompt's chunks in `name`'s affinity
        history (LRU, bounded at sticky_history)."""
        if not req.fp:
            return
        seen = self._affinity.setdefault(name, {})
        for h in req.fp:
            seen.pop(h, None)          # re-insert at the MRU end
            seen[h] = None
        while len(seen) > self.sticky_history:
            seen.pop(next(iter(seen)))

    def _dispatch(self, now: float) -> bool:
        if not self.pending:
            return False        # nothing to place — don't pay the
                                # per-replica telemetry reads at all
        did = False
        # score each dispatchable replica ONCE per dispatch round (a
        # telemetry/summary read per candidate per pending request
        # would make the hot path O(pending x replicas) lock+quantile
        # work); the in-flight component advances incrementally as
        # requests land
        scores = {n: self._score(n, now)
                  for n, e in self._replicas.items()
                  if e["alive"] and not e["draining"]
                  and not e["retired"] and not e["warming"]
                  and self._breakers[n].state == "closed"}
        while self.pending:
            req = self.pending[0]
            # sticky: fold the bounded prefix-affinity bonus into the
            # per-request ranking (scores themselves stay load-only —
            # the +1.0 landing bump below keeps overriding locality)
            ranked = sorted((n for n in scores if n != req.exclude),
                            key=lambda n: (scores[n]
                                           - self._affinity_bonus(n, req),
                                           n))
            if not ranked and req.exclude is not None:
                # nowhere else to go. If this is a TIMEOUT failover
                # and its old replica is still up, the work is still
                # running there (same rid) — re-attach instead of
                # re-submitting a duplicate; a death failover's old
                # engine is gone, so re-submission is safe
                name = req.exclude
                if req.failover_reason == "timeout" and name in scores:
                    self.pending.popleft()
                    self._reattach(req, name, now)
                    did = True
                    continue
                ranked = sorted(scores, key=lambda n: (scores[n], n))
            sent = False
            # one dispatch span per dispatch round; the engine's
            # lifecycle spans parent to it, so a failover's re-prefill
            # hangs off the RE-dispatch, not the original
            span_k = new_span_id()
            attempt_next = req.attempt + 1
            payload = {"id": req.rid,
                       "prompt": [int(t) for t in req.prompt],
                       "max_new": req.max_new,
                       "temperature": req.temp, "seed": req.seed,
                       "generated": list(req.tokens),
                       "trace": req.trace, "parent": span_k,
                       "attempt": attempt_next}
            for name in ranked:
                # pre-POST clock pair: the ONLY router stamp that
                # happens-before the replica's lifecycle "submit"
                # (the route/failover event itself is emitted AFTER
                # the gateway accepted, i.e. after that stamp) — the
                # stitcher's skew fit needs this lower bound, and
                # pre->event brackets one dispatch transaction
                # (telemetry/tracing._fit_offsets)
                pre_wall, pre_mono = time.time(), time.monotonic()
                try:
                    resp = self._replicas[name]["handle"].submit(
                        payload)
                except (OSError, ConnectionError):
                    self._breakers[name].note_failure(now)
                    continue
                self._breakers[name].note_success(now)
                err = (resp or {}).get("error")
                if err:
                    if "duplicate" in str(err):
                        # the replica already holds this rid: a prior
                        # failover left live work there (it survived
                        # while the request bounced elsewhere) —
                        # re-attach to it rather than terminally
                        # rejecting a request another engine is about
                        # to finish
                        self.pending.popleft()
                        self._reattach(req, name, now)
                        sent = did = True
                        break
                    if str(err).startswith(("ValueError", "KeyError",
                                            "TypeError")):
                        self.pending.popleft()
                        self._finalize(req, now, status="rejected",
                                       error=err)
                        sent = True     # consumed (terminally)
                        break
                    continue    # draining/overloaded: try the next
                self.pending.popleft()
                req.replica = name
                req.dispatch_t = now
                req.last_progress_t = now
                req.attempt = attempt_next
                self.inflight[req.rid] = req
                scores[name] = scores.get(name, 0.0) + 1.0
                # snapshot the bonus that influenced THIS ranking
                # before the landing itself is recorded into history
                aff = self._affinity_bonus(name, req)
                if self.sticky:
                    self._note_affinity(name, req)
                if req.failover_from is not None:
                    req.failovers += 1
                    self.counters["failovers"] += 1
                    self._emit("failover", id=req.rid, replica=name,
                               reason=req.failover_reason or "?",
                               tokens_done=len(req.tokens),
                               attempt=req.attempt,
                               trace=req.trace, span=span_k,
                               parent=req.span,
                               dispatch_wall=round(pre_wall, 6),
                               dispatch_mono=round(pre_mono, 6),
                               **{"from": req.failover_from})
                    req.failover_from = None
                    req.failover_reason = None
                else:
                    self.counters["routes"] += 1
                    extra_route = {}
                    if self.sticky:
                        extra_route["affinity"] = round(aff, 3)
                    self._emit("route", id=req.rid, replica=name,
                               queue_depth=len(self.pending),
                               score=round(scores[name] - 1.0, 3),
                               **extra_route,
                               trace=req.trace, span=span_k,
                               parent=req.span,
                               dispatch_wall=round(pre_wall, 6),
                               dispatch_mono=round(pre_mono, 6),
                               # fresh clock, not the step-loop
                               # `now`: the stitcher derives the
                               # fleet-edge submit time as (this
                               # line's log stamp - wait_ms), so
                               # wait_ms must be measured AT emission
                               # or the dispatch lag (an in-process
                               # engine compile) lands in rq_queue
                               wait_ms=round(
                                   (self.clock() - req.submit_t)
                                   * 1e3, 3))
                sent = did = True
                break
            if not sent:
                break               # no capacity now; retry next step
        return did

    def _reattach(self, req: _RouterReq, name: str,
                  now: float) -> None:
        """Bind a failed-over request back onto a replica that is
        still (or already) running it — timeout failovers with
        nowhere else to go, and duplicate-id replies from a replica a
        previous failover left the work on."""
        req.replica = name
        req.last_progress_t = now
        req.failover_from = None
        req.failover_reason = None
        self.inflight[req.rid] = req

    # ----------------------------------------------------- scale down

    def _start_drain(self, name: str, now: float,
                     reason: str) -> None:
        entry = self._replicas[name]
        entry["draining"] = True
        self._emit("scale", action="drain", replica=name,
                   reason=reason,
                   n_replicas=len(self.replica_names()))
        try:
            entry["handle"].drain()
        except (OSError, ConnectionError):
            pass                     # re-asked in _drain_progress

    def _drain_progress(self, now: float) -> None:
        for name, entry in list(self._replicas.items()):
            if not entry["draining"] or entry["retired"] \
                    or not entry["alive"]:
                continue
            if any(r.replica == name for r in self.inflight.values()):
                continue             # router-tracked work still there
            try:
                resp = entry["handle"].drain()
            except (OSError, ConnectionError):
                continue
            if resp.get("done") or resp.get("pending") == 0:
                # in-process handles report drained synchronously;
                # subprocess replicas exit 0 instead and land in
                # _supervise's "clean" branch
                self._finish_drain(name, now)

    def _finish_drain(self, name: str, now: float) -> None:
        entry = self._replicas[name]
        entry["retired"] = True
        entry["alive"] = False
        try:
            entry["handle"].stop()
        except Exception:
            pass
        if self.collector is not None:
            try:
                self.collector.deregister_replica({"name": name})
            except Exception:
                pass
        self.counters["scale_downs"] += 1
        self._emit("scale", action="down", replica=name,
                   reason="drained",
                   n_replicas=len(self.replica_names()))

    # ------------------------------------------------------- SLO/scale

    def _evaluate_rules(self, now: float) -> None:
        for rule in self.rules:
            rec = rule.evaluate(now)
            if rec is not None:
                self._emit("alert", **rec)

    def _autoscale(self, now: float) -> None:
        if not self.autoscale:
            return
        critical = any(r.state == "critical" for r in self.rules)
        if critical:
            self._idle_since = None
            if self._crit_since is None:
                self._crit_since = now
            elif (now - self._crit_since >= self.scale_hold_s
                  and now - self._last_scale_t >= self.scale_cooldown_s
                  and len(self.replica_names()) < self.max_replicas):
                burn = max((r.burn(r.fast_s, now) for r in self.rules
                            if r.sketch is not None), default=0.0)
                name = self._add_replica(now)
                self._last_scale_t = now
                self._crit_since = None
                self.counters["scale_ups"] += 1
                self._emit("scale", action="up", replica=name,
                           reason="burn", burn=round(burn, 3),
                           n_replicas=len(self.replica_names()))
            return
        self._crit_since = None
        busy = bool(self.unfinished())
        if busy:
            self._idle_since = None
            return
        if self._idle_since is None:
            self._idle_since = now
            return
        if (now - self._idle_since >= self.idle_drain_s
                and now - self._last_scale_t >= self.scale_cooldown_s
                and len(self.replica_names()) > self.min_replicas):
            live = [n for n in self.replica_names(live_only=True)]
            if not live:
                return
            # newest replica drains first (LIFO scale) — by spawn
            # index, not name string ("r9" > "r10" lexically)
            victim = max(live, key=lambda n: (int(n[1:])
                                              if n[1:].isdigit()
                                              else -1, n))
            self._last_scale_t = now
            self._idle_since = None
            self._start_drain(victim, now, reason="idle")

    # availability feed: called by _supervise at respawn-ready with
    # the measured downtime — split out so the stamp and the rule can
    # never disagree
    def _note_downtime(self, seconds: float, now: float) -> None:
        for rule in self.rules:
            if rule.sketch is None:
                rule.record_down(float(seconds), now)
