"""Streaming tokenized-shard corpus — the transformer family's L0.

The reference's data layer is strided in-RAM shards with resumable
arithmetic (`/root/reference/shallowspeed/dataset.py:52-80`); the LM
side until round 4 read `--text` whole into RAM (the endurance run was
17 epochs over a 1.75M-token file — data-bound). This module is the
same L0 discipline at corpus scale:

- **Shards on disk, memmapped**: `shard_0000.bin ...` raw
  little-endian token ids (uint16 when vocab fits, else uint32) plus
  `index.json` (dtype, per-shard token counts, vocab, the builder's
  settings). Nothing is loaded eagerly; a batch touches only the
  windows it reads.
- **Deterministic, checkpoint-resumable order**: `batch(step)` is a
  PURE function of (seed, step) — the same exact-replay property the
  seeded `--text` sampler proved across the endurance restart, held
  without materializing an index. Two orders:
  - "perm" (default): step-major walk of an affine permutation
    `w = (a*j + c) mod N` over all N windows (a coprime to N; a, c
    drawn per epoch from (seed, epoch)) — every window exactly once
    per epoch, reshuffled each epoch, O(1) state.
  - "random": i.i.d. (shard, start) per row — the `--text` sampler's
    semantics for corpora where window alignment shouldn't matter.
- **Held-out split protocol**: the builder carves the LAST
  `val_fraction` of tokens into `val.bin` BEFORE sharding, so train
  windows can never leak into validation; `val_batch` draws from it
  with the same pure-seeded addressing.

Windows are non-overlapping seq_len+1 slices WITHIN a shard (the +1
feeds the shifted target); the at-most-seq_len tail of each shard is
dropped, like the reference drops the non-divisible batch tail.
"""

from __future__ import annotations

import functools
import json
from pathlib import Path

import numpy as np

_INDEX = "index.json"
_VAL = "val.bin"


def _token_dtype(vocab: int):
    return np.uint16 if vocab <= (1 << 16) else np.uint32


def build_shards(tokens: np.ndarray, out_dir, vocab: int,
                 shard_tokens: int = 1 << 24,
                 val_fraction: float = 0.0, meta: dict | None = None,
                 val: np.ndarray | None = None) -> Path:
    """Write `tokens` (1-D int array) as a shard directory. The val
    split (if any) is the corpus TAIL, written to its own file before
    sharding — train/val windows are disjoint by construction. Pass
    `val` explicitly when the caller already split the corpus (e.g. the
    BPE builder splits BYTES before encoding so the tokenizer never
    sees held-out text); otherwise `val_fraction` carves the token
    tail here."""
    out = Path(out_dir)
    out.mkdir(parents=True, exist_ok=True)
    tokens = np.asarray(tokens)
    assert tokens.ndim == 1 and len(tokens) > 0, tokens.shape
    assert int(tokens.min()) >= 0 and int(tokens.max()) < vocab, (
        tokens.min(), tokens.max(), vocab)
    dt = _token_dtype(vocab)
    assert val is None or not val_fraction, (
        "pass EITHER an explicit val array or val_fraction")
    if val is not None:
        val = np.asarray(val)
        n_val = len(val)
        assert n_val > 0, "explicit val split is empty"
        # same range check train tokens get above: out-of-range ids
        # would silently WRAP in the narrowing astype below and only
        # surface as corrupt val batches much later
        assert int(val.min()) >= 0 and int(val.max()) < vocab, (
            f"explicit val ids outside [0, {vocab}): "
            f"min={int(val.min())}, max={int(val.max())}")
        val.astype(dt).tofile(out / _VAL)
    else:
        n_val = int(len(tokens) * val_fraction)
        if val_fraction:
            assert 0 < n_val < len(tokens), (
                f"val_fraction={val_fraction} of {len(tokens)} tokens "
                f"leaves no usable split")
            tokens, tail = tokens[:-n_val], tokens[-n_val:]
            tail.astype(dt).tofile(out / _VAL)
    counts = []
    for i, start in enumerate(range(0, len(tokens), shard_tokens)):
        chunk = tokens[start:start + shard_tokens]
        chunk.astype(dt).tofile(out / f"shard_{i:04d}.bin")
        counts.append(len(chunk))
    (out / _INDEX).write_text(json.dumps({
        "dtype": np.dtype(dt).name, "vocab": int(vocab),
        "shard_tokens": counts, "val_tokens": n_val,
        **(meta or {})}))
    return out


class TokenShards:
    """Memmapped random-access view of a shard directory (see module
    docstring for the order/split contracts)."""

    def __init__(self, data_dir, seq_len: int):
        self.dir = Path(data_dir)
        idx = json.loads((self.dir / _INDEX).read_text())
        self.vocab = int(idx["vocab"])
        self.seq_len = int(seq_len)
        dt = np.dtype(idx["dtype"])
        self._mms = []
        for i, n in enumerate(idx["shard_tokens"]):
            mm = np.memmap(self.dir / f"shard_{i:04d}.bin", dtype=dt,
                           mode="r")
            assert len(mm) == n, (i, len(mm), n)
            self._mms.append(mm)
        self._val = (np.memmap(self.dir / _VAL, dtype=dt, mode="r")
                     if idx.get("val_tokens") else None)
        # non-overlapping (seq_len+1)-windows per shard; cumulative
        # counts give O(log S) window -> (shard, offset) addressing
        w = self.seq_len + 1
        self._wins = np.array([len(m) // w for m in self._mms])
        assert self._wins.sum() > 0, (
            f"no shard holds a full seq_len+1={w} window")
        self._cum = np.concatenate([[0], np.cumsum(self._wins)])
        self.n_windows = int(self._wins.sum())

    # ------------------------------------------------------- addressing

    def _window(self, w: int) -> np.ndarray:
        s = int(np.searchsorted(self._cum, w, side="right")) - 1
        off = (w - int(self._cum[s])) * (self.seq_len + 1)
        return np.asarray(
            self._mms[s][off:off + self.seq_len + 1], np.int32)

    @staticmethod
    @functools.lru_cache(maxsize=64)
    def _perm_params(n: int, seed: int, epoch: int):
        """Affine permutation of range(n): j -> (a*j + c) % n with
        gcd(a, n) == 1 — a full-cycle reshuffle in O(1) state. Cached:
        every row of a batch (and every batch of an epoch) reuses one
        (a, c) pair."""
        if n == 1:  # single-window corpus: the only permutation
            return 1, 0
        rng = np.random.default_rng([seed, 0x5eed, epoch])
        while True:
            a = int(rng.integers(1, n)) | 1  # odd helps; still verify
            if np.gcd(a, n) == 1:
                break
        c = int(rng.integers(0, n))
        return a, c

    # ---------------------------------------------------------- batches

    def batch(self, step: int, batch_size: int, seed: int = 0,
              order: str = "perm"):
        """(tokens, targets) (B, T) int32 for `step` — pure in
        (seed, step), so a resumed run replays the exact stream."""
        t = self.seq_len
        if order == "perm":
            n = self.n_windows
            rows = []
            for i in range(batch_size):
                j = step * batch_size + i
                epoch, k = divmod(j, n)
                a, c = self._perm_params(n, seed, epoch)
                rows.append(self._window((a * k + c) % n))
            win = np.stack(rows)
        else:
            assert order == "random", order
            rng = np.random.default_rng([seed, step])
            ws = rng.integers(0, self.n_windows, batch_size)
            win = np.stack([self._window(int(w)) for w in ws])
        return win[:, :t].copy(), win[:, 1:t + 1].copy()

    def val_batch(self, step: int, batch_size: int, seed: int = 0):
        """Held-out batch from val.bin (random starts — the val tail is
        one stream, matching the --text val sampler's semantics)."""
        assert self._val is not None, (
            f"{self.dir} was built without a val split "
            f"(build_shards(val_fraction=...))")
        t = self.seq_len
        assert len(self._val) > t + 1, "val split shorter than seq_len"
        rng = np.random.default_rng([seed, step])
        starts = rng.integers(0, len(self._val) - t - 1, batch_size)
        tok = np.stack([np.asarray(self._val[s:s + t], np.int32)
                        for s in starts])
        tgt = np.stack([np.asarray(self._val[s + 1:s + t + 1], np.int32)
                        for s in starts])
        return tok, tgt

    @property
    def has_val(self) -> bool:
        return self._val is not None

    @property
    def val_tokens(self) -> int:
        """Held-out split length (0 when absent) — public so drivers
        can fail fast on undersized splits without reaching into the
        memmap."""
        return 0 if self._val is None else len(self._val)


class ValSplit:
    """Duck-typed like `TokenShards.batch` so the driver's one batch
    path serves both streams (`train_lm.make_batch` dispatches on the
    `.batch` attribute)."""

    def __init__(self, shards: TokenShards):
        self._s = shards

    def batch(self, step: int, batch_size: int, seed: int = 0,
              order: str = "perm"):
        return self._s.val_batch(step, batch_size, seed)
