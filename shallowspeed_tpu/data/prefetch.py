"""Async host->device input pipeline.

The reference's data path is synchronous: every microbatch load is a
blocking slice copy on the critical path
(`/root/reference/shallowspeed/dataset.py:66-80`, called per-instruction
from the Worker, `pipe.py:355-365`). On TPU the equivalent stall is worse:
if the host only starts building + transferring batch N+1 after batch N's
step returns, the chip idles for the whole host time every step.

`DevicePrefetcher` overlaps the three stages the TPU way:

- a daemon thread pulls from the (host-side) batch iterator and immediately
  *places* each batch — `device_put`/`place_global` are async in JAX, so
  the H2D DMA streams while the device computes;
- a bounded queue keeps up to `depth` placed batches in flight (depth 2 =
  classic double buffering: one computing, one transferring);
- together with the engines' `train_batch_async` (loss returned as a lazy
  device value instead of a blocking `float()`), the dispatch loop never
  waits on the host: XLA's async dispatch queues step N+1 while N runs.

Producer exceptions are captured and re-raised at the consuming end, so
error behavior matches the synchronous loop.
"""

from __future__ import annotations

import queue
import threading
from typing import Any, Callable, Iterable, Iterator

_DONE = object()


class DevicePrefetcher:
    """Iterate `it`, applying `place` to each item `depth` items ahead.

    `place` maps one host batch (any pytree of numpy arrays) to its placed
    form; it runs on the producer thread. Iteration order is preserved.
    """

    def __init__(self, it: Iterable[Any], place: Callable[[Any], Any],
                 depth: int = 2):
        assert depth >= 1
        self._q: queue.Queue = queue.Queue(maxsize=depth)
        self._err: BaseException | None = None
        self._done = False
        self._stop = threading.Event()

        def put(item) -> bool:
            """Bounded put that gives up when close() signals; returns
            False to end the producer."""
            while not self._stop.is_set():
                try:
                    self._q.put(item, timeout=0.05)
                    return True
                except queue.Full:
                    continue
            return False

        def produce():
            try:
                for item in it:
                    if self._stop.is_set() or not put(place(item)):
                        return
            except BaseException as e:  # re-raised on the consumer side
                self._err = e
            finally:
                put(_DONE)

        self._thread = threading.Thread(target=produce, daemon=True)
        self._thread.start()

    def close(self) -> None:
        """Stop the producer and release queued (device) batches. Safe to
        call any time; consumers abandoning iteration early (errors,
        breaks) should close() — e.g. in a `finally:` — so up-to-`depth`
        placed batches don't stay pinned in device memory."""
        self._stop.set()
        self._done = True

        def drain():
            while True:
                try:
                    self._q.get_nowait()
                except queue.Empty:
                    return

        drain()  # unblock a producer parked in put()
        self._thread.join(timeout=5)
        drain()  # a pending put may have slipped in before the stop check

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()
        return False

    def __iter__(self) -> Iterator[Any]:
        return self

    def __next__(self) -> Any:
        if self._done:  # exhausted (or errored): stay terminated, never
            raise StopIteration  # block on a queue no producer feeds
        item = self._q.get()
        if item is _DONE:
            self._done = True
            self._thread.join()
            if self._err is not None:
                raise self._err
            raise StopIteration
        return item


def prefetch_to_device(it: Iterable[Any], place: Callable[[Any], Any],
                       depth: int = 2) -> Iterator[Any]:
    """Functional spelling of `DevicePrefetcher` (depth<=0 disables —
    returns the plain mapped iterator, same semantics, no thread)."""
    if depth <= 0:
        return (place(item) for item in it)
    return DevicePrefetcher(it, place, depth)


def sync_every(step: int, every: int, total: int) -> bool:
    """Whether the driver should force a host sync at `step` (log points
    and the final step). Keeping float(loss) off the other steps is what
    lets dispatch run ahead."""
    return step % every == 0 or step == total - 1


__all__ = ["DevicePrefetcher", "prefetch_to_device", "sync_every"]
