"""Byte-level BPE tokenizer — trained, saved, and loaded by the framework.

The reference has no text pipeline at all (its data layer is MNIST
vectors, `/root/reference/shallowspeed/dataset.py`); the LM driver here
started byte-level (vocab 256). This module adds the standard subword
step: byte-pair encoding over UTF-8 bytes (GPT-2's scheme, minus the
regex pre-tokenizer — chunks split on whitespace with the space glued to
the following word, so merges never cross word boundaries).

Design points:
- Base alphabet is all 256 bytes, so ANY input encodes losslessly and
  decode is exact byte reconstruction — no <unk>, no normalization.
- `train` counts pair frequencies over unique chunks (frequency-weighted),
  merging the most frequent pair until `vocab_size`; pure NumPy/Python,
  fine for the corpus sizes a single-host text file reaches.
- `encode` caches per-chunk tokenizations, so repeated words cost one
  merge pass; returns int32 ids ready for the LM engines.
- Persistence is one JSON file (the merge list) — saved next to
  checkpoints so `--sample-only` restores text fidelity with the model.
"""

from __future__ import annotations

import json
import re
from pathlib import Path

import numpy as np

_CHUNK = re.compile(rb"\s*\S+|\s+")


def _chunks(data: bytes) -> list[bytes]:
    return _CHUNK.findall(data)


class ByteBPE:
    """Byte-level BPE: ids 0..255 are raw bytes, id 256+i is merge i."""

    def __init__(self, merges: list[tuple[int, int]]):
        self.merges = [tuple(m) for m in merges]
        self._rank = {pair: i for i, pair in enumerate(self.merges)}
        # id -> bytes it expands to (built up in merge order)
        self._bytes = [bytes([i]) for i in range(256)]
        for a, b in self.merges:
            self._bytes.append(self._bytes[a] + self._bytes[b])
        self._cache: dict[bytes, list[int]] = {}

    @property
    def vocab_size(self) -> int:
        return 256 + len(self.merges)

    # ------------------------------------------------------------ encode

    def _merge_chunk(self, chunk: bytes) -> list[int]:
        ids = list(chunk)
        while len(ids) > 1:
            best, best_rank = None, None
            for pair in zip(ids, ids[1:]):
                r = self._rank.get(pair)
                if r is not None and (best_rank is None or r < best_rank):
                    best, best_rank = pair, r
            if best is None:
                break
            new_id = 256 + best_rank
            out, i = [], 0
            while i < len(ids):
                if (i + 1 < len(ids)
                        and (ids[i], ids[i + 1]) == best):
                    out.append(new_id)
                    i += 2
                else:
                    out.append(ids[i])
                    i += 1
            ids = out
        return ids

    def encode(self, text) -> np.ndarray:
        data = text.encode() if isinstance(text, str) else bytes(text)
        out: list[int] = []
        for chunk in _chunks(data):
            got = self._cache.get(chunk)
            if got is None:
                got = self._merge_chunk(chunk)
                self._cache[chunk] = got
            out.extend(got)
        return np.asarray(out, np.int32)

    def decode(self, ids) -> str:
        return self.decode_bytes(ids).decode("utf-8", errors="replace")

    def decode_bytes(self, ids) -> bytes:
        return b"".join(self._bytes[int(i)] for i in np.asarray(ids).ravel())

    # ------------------------------------------------------- persistence

    def save(self, path) -> None:
        Path(path).write_text(json.dumps(
            {"kind": "byte_bpe", "merges": self.merges}))

    @classmethod
    def load(cls, path) -> "ByteBPE":
        head = json.loads(Path(path).read_text())
        assert head.get("kind") == "byte_bpe", head.get("kind")
        return cls([tuple(m) for m in head["merges"]])


def train_bpe(text, vocab_size: int) -> ByteBPE:
    """Train a ByteBPE to `vocab_size` (>= 256) on `text` (str or bytes).

    Frequency-weighted over unique whitespace chunks, INCREMENTAL
    (round 4): the original trainer recounted every pair over every
    word per merge — O(vocab_size x corpus vocabulary), ~6 hours for a
    32k vocab on a 10 MB corpus, which blocked the flagship config's
    tokenizer. This form keeps global pair counts, a pair -> words
    index, and a lazy max-heap: each merge touches only the words that
    CONTAIN the merged pair and pushes refreshed heap entries for the
    pairs whose counts changed (stale entries are discarded on pop —
    the standard BPE trainer structure). 32k merges on the same corpus
    now take ~2 minutes. Deterministic: ties on count break toward the
    smaller (a, b) pair id tuple. Stops early if no pair repeats."""
    import heapq

    assert vocab_size >= 256, vocab_size
    data = text.encode() if isinstance(text, str) else bytes(text)
    counts: dict[bytes, int] = {}
    for c in _chunks(data):
        counts[c] = counts.get(c, 0) + 1
    words, wfreq = [], []
    for c, n in counts.items():
        words.append(list(c))
        wfreq.append(n)

    pair_counts: dict[tuple[int, int], int] = {}
    pair_words: dict[tuple[int, int], set[int]] = {}
    for w, (ids, n) in enumerate(zip(words, wfreq)):
        for pair in zip(ids, ids[1:]):
            pair_counts[pair] = pair_counts.get(pair, 0) + n
            pair_words.setdefault(pair, set()).add(w)

    # lazy heap: entries are (-count, pair); an entry is valid only if
    # its count still matches pair_counts (stale ones pop and drop)
    heap = [(-n, p) for p, n in pair_counts.items()]
    heapq.heapify(heap)

    def bump(pair, delta, w):
        n = pair_counts.get(pair, 0) + delta
        if n <= 0:
            pair_counts.pop(pair, None)
            return
        pair_counts[pair] = n
        if delta > 0:
            pair_words.setdefault(pair, set()).add(w)
            heapq.heappush(heap, (-n, pair))

    merges: list[tuple[int, int]] = []
    while 256 + len(merges) < vocab_size and heap:
        # pop to the highest CURRENT count; among equal counts the heap
        # yields the smallest pair tuple (deterministic tie-break)
        neg, best = heapq.heappop(heap)
        cur = pair_counts.get(best, 0)
        if -neg != cur:
            if cur > 0:  # stale entry; re-push at the true count
                heapq.heappush(heap, (-cur, best))
            continue
        if cur < 2:
            break  # nothing repeats; further merges are memorization
        new_id = 256 + len(merges)
        merges.append(best)
        touched = pair_words.pop(best, set())
        pair_counts.pop(best, None)
        for w in touched:
            ids, n = words[w], wfreq[w]
            i = 0
            while i < len(ids) - 1:
                if (ids[i], ids[i + 1]) != best:
                    i += 1
                    continue
                # neighbors lose their old pairing, gain the merged id
                if i > 0:
                    bump((ids[i - 1], ids[i]), -n, w)
                    bump((ids[i - 1], new_id), n, w)
                if i + 2 < len(ids):
                    bump((ids[i + 1], ids[i + 2]), -n, w)
                    bump((new_id, ids[i + 2]), n, w)
                ids[i:i + 2] = [new_id]
    return ByteBPE(merges)
