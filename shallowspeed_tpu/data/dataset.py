"""Dataset with strided DP sharding and microbatch slicing — the L0 layer.

Reference: `/root/reference/shallowspeed/dataset.py:5-86`. Semantics kept
exactly:

- drop-last to a multiple of the **global** batch size (`dataset.py:52`);
- **strided** DP shard `input_X[rank:end:size].copy()` — the `.copy()` keeps
  shards C-contiguous for matmul performance (`dataset.py:54-58`);
- microbatch slicing by `(batch_id, mubatch_id)` offsets into the local
  shard (`dataset.py:66-80`);
- divisibility asserts (`dataset.py:35-38,60-61`).

TPU-native addition: `load_mubatch_stack` / `stack_epoch` return whole
(n_mu, mubs, d) / (n_batches, dp, n_mu, mubs, d) stacks so the fused engines
can `device_put` a batch — or a whole epoch — once and `lax.scan` over it on
device, instead of the reference's per-microbatch host loads.
"""

from __future__ import annotations

from pathlib import Path

import numpy as np


class Dataset:
    """One DP rank's view of the on-disk dataset.

    `Dataset(save_dir, global_batch_size, mubatch_size, validation=False)`
    then `.load(DP_rank, DP_size)` (returns self) — mirroring
    `dataset.py:19-64`.
    """

    def __init__(self, save_dir, global_batch_size: int, mubatch_size: int,
                 validation: bool = False):
        self.save_dir = Path(save_dir)
        self.global_batch_size = global_batch_size
        self.mubatch_size = mubatch_size
        self.validation = validation
        self.input_X: np.ndarray | None = None
        self.target_Y: np.ndarray | None = None
        self._local_bs: int | None = None

    # ---------------------------------------------------------------- load

    def load(self, DP_rank: int, DP_size: int) -> "Dataset":
        assert self.global_batch_size % DP_size == 0, (
            f"global batch {self.global_batch_size} not divisible by "
            f"DP={DP_size}")
        local_bs = self.global_batch_size // DP_size
        assert local_bs % self.mubatch_size == 0, (
            f"local batch {local_bs} not divisible by microbatch "
            f"{self.mubatch_size}")
        self._local_bs = local_bs

        split = "val" if self.validation else "train"
        x = np.load(self.save_dir / f"x_{split}.npy").astype(np.float32)
        y = np.load(self.save_dir / f"y_{split}.npy").astype(np.float32)

        # drop-last to a multiple of the global batch (`dataset.py:52`)
        n_full = len(x) - (len(x) % self.global_batch_size)
        # strided shard; .copy() for contiguity (`dataset.py:54-58`)
        self.input_X = x[DP_rank:n_full:DP_size].copy()
        self.target_Y = y[DP_rank:n_full:DP_size].copy()
        return self

    # ------------------------------------------------------------- queries

    def __len__(self) -> int:
        assert self.input_X is not None, "call .load() first"
        return len(self.input_X)

    def get_num_batches(self) -> int:
        return len(self) // self._local_bs

    def get_num_mubatches(self) -> int:
        return self._local_bs // self.mubatch_size

    # ------------------------------------------------------------- slicing

    def _mubatch_slice(self, batch_id: int, mubatch_id: int) -> slice:
        start = batch_id * self._local_bs + mubatch_id * self.mubatch_size
        return slice(start, start + self.mubatch_size)

    def load_micro_batch_input(self, batch_id: int, mubatch_id: int) -> np.ndarray:
        return self.input_X[self._mubatch_slice(batch_id, mubatch_id)]

    def load_micro_batch_target(self, batch_id: int, mubatch_id: int) -> np.ndarray:
        return self.target_Y[self._mubatch_slice(batch_id, mubatch_id)]

    def load_batch(self, batch_id: int) -> tuple[np.ndarray, np.ndarray]:
        """The whole local batch: (local_bs, 784), (local_bs, 10)."""
        from shallowspeed_tpu import chaos

        # chaos stall fault (fires at most once per plan): a wedged
        # data loader must surface in the goodput ledger / hang
        # detection, not silently stretch the epoch time
        chaos.on_data_load(batch_id)
        s = slice(batch_id * self._local_bs, (batch_id + 1) * self._local_bs)
        return self.input_X[s], self.target_Y[s]

    def load_mubatch_stack(self, batch_id: int) -> tuple[np.ndarray, np.ndarray]:
        """(n_mu, mubs, d_in), (n_mu, mubs, d_out) — one device_put per batch."""
        x, y = self.load_batch(batch_id)
        n_mu = self.get_num_mubatches()
        return (x.reshape(n_mu, self.mubatch_size, -1),
                y.reshape(n_mu, self.mubatch_size, -1))


def stack_epoch(datasets: list[Dataset], n_batches: int | None = None
                ) -> tuple[np.ndarray, np.ndarray]:
    """Stack an epoch across DP shards: (n_batches, dp, n_mu, mubs, d).

    Feeds the fused engines' epoch scan — the whole epoch becomes
    HBM-resident in one transfer, replacing per-microbatch host loads
    (`dataset.py:66-80`).
    """
    if n_batches is None:
        n_batches = datasets[0].get_num_batches()
    xs, ys = [], []
    for b in range(n_batches):
        stacks = [ds.load_mubatch_stack(b) for ds in datasets]
        xs.append(np.stack([s[0] for s in stacks]))
        ys.append(np.stack([s[1] for s in stacks]))
    return np.stack(xs), np.stack(ys)
