"""L0 data layer: dataset sharding + MNIST preparation.

Reference: `/root/reference/shallowspeed/dataset.py` and
`/root/reference/download_dataset.py`.
"""

from shallowspeed_tpu.data.dataset import Dataset, stack_epoch
from shallowspeed_tpu.data.mnist import ensure_mnist, prepare_mnist, synthesize_mnist

__all__ = [
    "Dataset",
    "stack_epoch",
    "ensure_mnist",
    "prepare_mnist",
    "synthesize_mnist",
]
