"""MNIST-784 preparation — the `download_dataset.py` capability.

Reference: `/root/reference/download_dataset.py:9-23` fetches MNIST-784 from
OpenML, normalizes (`x /= 255; x -= mean`), one-hot-encodes targets, splits
85/15 train/val, and writes files the `Dataset` loader reads back.

This environment is air-gapped, so the OpenML fetch is attempted only when
explicitly allowed and falls back to a **deterministic synthetic MNIST-784**:
10 fixed class prototypes + Gaussian noise, normalized to the same scale as
the real data. The synthetic task is linearly-separable-ish so training
accuracy is a meaningful signal in tests (SURVEY §4).

Files written (npy instead of parquet — no pandas/pyarrow dependency, same
role as `x_{train,val}.parquet` + `y_{train,val}.npy`):
    x_train.npy  (n_train, 784) float32
    y_train.npy  (n_train, 10)  float32 one-hot
    x_val.npy    (n_val, 784)   float32
    y_val.npy    (n_val, 10)    float32 one-hot
"""

from __future__ import annotations

from pathlib import Path

import numpy as np

FILES = ("x_train.npy", "y_train.npy", "x_val.npy", "y_val.npy")
VAL_FRACTION = 0.15  # reference `download_dataset.py:18` test_size=0.15


def synthesize_mnist(n_samples: int = 70000) -> tuple[np.ndarray, np.ndarray]:
    """Deterministic synthetic MNIST-784: (x (n,784) f32, y (n,10) one-hot f32).

    Class prototypes are fixed by a hard-coded seed, so two calls with the
    same `n_samples` produce bit-identical arrays (required by the dataset
    equivalence tests, which rebuild shards independently per DP layout).
    """
    rng = np.random.default_rng(20240202)
    prototypes = rng.normal(0.0, 0.35, (10, 784)).astype(np.float32)
    labels = rng.integers(0, 10, n_samples)
    noise = rng.normal(0.0, 0.25, (n_samples, 784)).astype(np.float32)
    x = prototypes[labels] + noise
    # match the real data's normalization envelope (x/255 - mean ≈ zero-mean,
    # unit-ish scale after the prototypes' spread)
    x = (x - x.mean(axis=0, keepdims=True)).astype(np.float32)
    y = np.zeros((n_samples, 10), np.float32)
    y[np.arange(n_samples), labels] = 1.0
    return x, y


def _fetch_openml() -> tuple[np.ndarray, np.ndarray]:
    """Real MNIST-784 via sklearn (reference `download_dataset.py:9-16`).
    Raises on any failure (air-gapped hosts) — caller falls back."""
    from sklearn.datasets import fetch_openml  # type: ignore

    mnist = fetch_openml("mnist_784", version=1, as_frame=False)
    x = np.asarray(mnist.data, np.float32) / 255.0
    x -= x.mean(axis=0, keepdims=True)
    labels = np.asarray(mnist.target, int)
    y = np.zeros((len(labels), 10), np.float32)
    y[np.arange(len(labels)), labels] = 1.0
    return x, y


def prepare_mnist(save_dir, synthetic: bool | None = None,
                  n_samples: int = 70000) -> Path:
    """Write the four dataset files under `save_dir` and return it.

    synthetic=True  → always synthesize;
    synthetic=None  → try OpenML, fall back to synthetic (zero-egress hosts);
    synthetic=False → OpenML only (raises offline).
    """
    save_dir = Path(save_dir)
    save_dir.mkdir(parents=True, exist_ok=True)

    if synthetic:
        x, y = synthesize_mnist(n_samples)
    else:
        try:
            x, y = _fetch_openml()
        except Exception:
            if synthetic is False:
                raise
            x, y = synthesize_mnist(n_samples)

    n = len(x)
    n_val = int(n * VAL_FRACTION)
    n_train = n - n_val
    # deterministic shuffle before the split (reference uses
    # train_test_split(random_state=42), `download_dataset.py:18`)
    perm = np.random.default_rng(42).permutation(n)
    x, y = x[perm], y[perm]
    np.save(save_dir / "x_train.npy", x[:n_train])
    np.save(save_dir / "y_train.npy", y[:n_train])
    np.save(save_dir / "x_val.npy", x[n_train:])
    np.save(save_dir / "y_val.npy", y[n_train:])
    return save_dir


def ensure_mnist(save_dir) -> Path:
    """Idempotent prepare: reuse existing files, else create them."""
    save_dir = Path(save_dir)
    if all((save_dir / f).exists() for f in FILES):
        return save_dir
    return prepare_mnist(save_dir)
