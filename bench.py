"""Benchmark: MNIST-MLP training throughput on the reference workload.

Workload = the reference's exact training config (`/root/reference/
train.py:56-59,98,107`): MLP [784,128,127,126,125,124,123,10], global batch
128, 4 microbatches, SGD lr=0.006, MSE-on-softmax.

The reference publishes no numbers (BASELINE.md), so the baseline is
*measured*: a pure-NumPy training step with identical math (forward,
hand-written backward, microbatch grad accumulation, SGD) — the same
substrate the reference dispatches to (NumPy + system BLAS,
`README.md:23`). `vs_baseline` = our samples/sec divided by the PINNED
NumPy number in BASELINE.json (`pinned_numpy_baseline`, recorded once as
the median of idle-host runs) so re-running bench.py gives a consistent
ratio; the live-host NumPy measurement is reported separately as
`numpy_live_sps` (it moves with host load and is diagnostics only).

The JSON line also carries the TPU-bar numbers: `transformer_mfu` /
`transformer_tflops` from an MXU-saturating transformer-LM config
(bf16 + flash attention, d_model 2048) measured as one fused multi-step
XLA dispatch — fraction-of-peak on the detected chip
(`shallowspeed_tpu/flops.py`), the metric the MLP workload is too small
to exercise.

Load robustness (round 6, VERDICT r5 weak #1: best-of-3 was evidently
load-sensitive — the r5 driver capture regressed ~14% below the
builder's re-run): the TPU and NumPy measurements now run as
INTERLEAVED rounds (t, n, t, n, ...) aggregated by MEDIAN, so a host
load transient hits both sides of the ratio instead of whichever
happened to be running, and a single spike cannot become the reported
number. The JSON records every round, the spread, and host-load
diagnostics (1/5/15-min loadavg, runnable-process count, cpu count)
with an `idle_host` verdict — a bench line captured under load now
SAYS so. Done-bar: two back-to-back runs agree within ±2% on
`vs_baseline` (pinned denominator) and `transformer_mfu`.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline", ...}.
"""

from __future__ import annotations

import json
import time
from pathlib import Path

import numpy as np

LAYER_SIZES = [784, 128, 127, 126, 125, 124, 123, 10]
GBS = 128
N_MU = 4
LR = 0.006
BENCH_BATCHES = 464   # full-epoch batch count of the 59,392-sample train set
EPOCHS = 20           # the reference's full run (`train.py:56`)


# --------------------------------------------------------- numpy baseline


def numpy_baseline_step_fn():
    """Reference-equivalent pure-NumPy training step (measured, not copied:
    same math as shallowspeed_tpu.ops.functional on the NumPy substrate)."""
    from shallowspeed_tpu.models.mlp import init_stage_params

    params = [{k: np.asarray(v) for k, v in layer.items()}
              for layer in init_stage_params(LAYER_SIZES)]
    n = len(params)

    def step(xs, ys):  # xs: (N_MU, mubs, 784); mutates `params` in place
        grads = [{"W": np.zeros_like(p["W"]), "b": np.zeros_like(p["b"])}
                 for p in params]
        for mu in range(N_MU):
            x, t = xs[mu], ys[mu]
            acts = [x]
            masks = []
            h = x
            for i, p in enumerate(params):
                z = h @ p["W"].T + p["b"]
                if i < n - 1:
                    masks.append(z > 0)
                    h = np.maximum(z, 0.0)
                else:
                    h = z
                acts.append(h)
            e = np.exp(h - h.max())
            probs = e / (e.sum(axis=1, keepdims=True) + 1e-7)
            dout = -2.0 * (t - probs) / GBS
            g = probs * dout
            dout = g - probs * g.sum(axis=-1, keepdims=True)
            for i in range(n - 1, -1, -1):
                if i < n - 1:
                    dout = dout * masks[i]
                grads[i]["W"] += dout.T @ acts[i]
                grads[i]["b"] += dout.sum(axis=0, keepdims=True)
                dout = dout @ params[i]["W"]
        for p, g in zip(params, grads):
            p["W"] -= LR * g["W"]
            p["b"] -= LR * g["b"]

    step.params = params  # exposed for the parity test (test_numpy_parity)
    return step


def numpy_round_fn(xs, ys, n_batches=60):
    """One warmed-up NumPy measurement round: () -> samples/sec over
    `n_batches` batches (the full 20-epoch run would take minutes)."""
    step = numpy_baseline_step_fn()
    for _ in range(3):
        step(xs, ys)  # warmup (allocator, BLAS thread pools)

    def one_round() -> float:
        t0 = time.perf_counter()
        for _ in range(n_batches):
            step(xs, ys)
        dt = time.perf_counter() - t0
        return n_batches * GBS / dt

    return one_round


def bench_numpy(xs, ys, n_batches=60, rounds=3) -> float:
    """Median sustained NumPy samples/sec (kept for parity tests and
    one-off use; `main` interleaves the rounds with the TPU side)."""
    one = numpy_round_fn(xs, ys, n_batches)
    return float(np.median([one() for _ in range(rounds)]))


# ------------------------------------------------------------ jax/tpu side


def tpu_round_fn(xs, ys, n_batches=BENCH_BATCHES):
    """One warmed-up TPU measurement round: () -> samples/sec for the
    whole EPOCHS-epoch run compiled into ONE XLA dispatch (scan over
    epochs of scan over batches), data HBM-resident. Staging and the
    compile are excluded from the timed region — the NumPy baseline's
    data is likewise pre-generated in RAM."""
    import jax

    from shallowspeed_tpu.engine import FusedDPEngine
    from shallowspeed_tpu.models.mlp import MLPStage
    from shallowspeed_tpu.optim import SGD
    from shallowspeed_tpu.parallel.mesh import make_mesh

    mesh = make_mesh(1, 1)
    stage = MLPStage(LAYER_SIZES, 0, 1, batch_size=GBS)
    eng = FusedDPEngine(stage, SGD(LR), mesh)

    class _DS:  # minimal adapter over pre-generated host arrays
        def get_num_batches(self):
            return n_batches

        def load_mubatch_stack(self, batch_id):
            return xs, ys

    def sync():
        # device_get of a small leaf forces a real round-trip sync;
        # block_until_ready alone does not drain the async dispatch queue
        # on tunneled backends.
        jax.device_get(eng.params[0]["b"])

    staged = eng.stage_epoch([_DS()])
    eng.train_run(staged, EPOCHS)  # compile warmup (excluded)
    sync()

    def one_round() -> float:
        t0 = time.perf_counter()
        eng.train_run(staged, EPOCHS)
        sync()
        dt = time.perf_counter() - t0
        return (EPOCHS * n_batches) * GBS / dt

    return one_round


def bench_tpu(xs, ys, n_batches=BENCH_BATCHES, rounds=3) -> float:
    """Median steady-state throughput (kept for one-off use; `main`
    interleaves the rounds with the NumPy side)."""
    one = tpu_round_fn(xs, ys, n_batches)
    return float(np.median([one() for _ in range(rounds)]))


# ----------------------------------------------------- load robustness


def host_load_diagnostics(self_load: float = 0.0) -> dict:
    """Who else is on this host right now: 1/5/15-min loadavg, the
    runnable-process count (/proc/stat procs_running), total process
    count, cpu count, and an `idle_host` verdict (1-min loadavg under
    half the cpus — plus `self_load`, the bench's own expected
    contribution, for the AFTER sample: minutes of interleaved rounds
    legitimately push loadavg by ~1 on a small host and must not make
    every run self-report as contaminated — and nothing else
    runnable; procs_running already excludes us via the +1). Recorded
    IN the bench JSON so a number captured under load says so — this
    host's own BASELINE.md documents 25x stalls from concurrent load."""
    import os

    ncpu = os.cpu_count() or 1
    try:
        la1, la5, la15 = os.getloadavg()
    except OSError:  # pragma: no cover — non-UNIX
        la1 = la5 = la15 = -1.0
    procs_running = None
    try:
        for line in open("/proc/stat"):
            if line.startswith("procs_running"):
                # includes this bench process itself
                procs_running = int(line.split()[1])
                break
    except OSError:  # pragma: no cover — non-Linux
        pass
    n_procs = None
    try:
        n_procs = sum(1 for d in os.listdir("/proc") if d.isdigit())
    except OSError:  # pragma: no cover — non-Linux
        pass
    idle = (la1 < 0.5 * ncpu + self_load
            and (procs_running is None or procs_running <= ncpu + 1))
    return {"loadavg": [round(la1, 2), round(la5, 2), round(la15, 2)],
            "cpus": ncpu, "procs_running": procs_running,
            "n_processes": n_procs, "idle_host": bool(idle)}


def interleaved_medians(round_fns: dict, rounds: int = 5,
                        max_extra: int = 4,
                        spread_target: float = 0.10,
                        gate: tuple = ()) -> dict:
    """Run each side's measurement round back-to-back within every
    round (t, n, t, n, ...) and aggregate by median: a load transient
    lands on both sides of the ratio instead of one, and one spike
    cannot become the reported number. When the spread ((max-min)/
    median) still exceeds `spread_target` after the base rounds — a
    load transient hit several rounds — up to `max_extra` additional
    interleaved rounds are run so the median sits on more samples.
    `gate` names the sides whose spread drives that extension (default:
    all); main() gates on the TPU side only — the numpy live number is
    diagnostics, and BLAS jitter alone must not buy four more full
    TPU rounds. Returns per-side {median, rounds, spread}."""
    samples: dict[str, list] = {k: [] for k in round_fns}

    def one_round():
        for name, fn in round_fns.items():
            samples[name].append(fn())

    def spread(vals):
        return (max(vals) - min(vals)) / float(np.median(vals))

    for _ in range(rounds):
        one_round()
    extra = 0
    gated = gate or tuple(round_fns)
    while extra < max_extra and any(
            spread(samples[k]) > spread_target for k in gated):
        one_round()
        extra += 1
    out = {}
    for name, vals in samples.items():
        out[name] = {
            "median": float(np.median(vals)),
            "rounds": [round(v, 1) for v in vals],
            "spread": round(spread(vals), 4),
        }
    return out


def bench_transformer_mfu():
    """MXU-saturating transformer-LM training MFU (see scripts/
    bench_mfu.py for the sweepable version). Returns {} off-TPU."""
    import argparse

    import jax

    if jax.default_backend() != "tpu":
        return {}
    import sys

    sys.path.insert(0, str(Path(__file__).resolve().parent / "scripts"))
    from bench_mfu import run as mfu_run

    r = mfu_run(argparse.Namespace(
        vocab=256, d_model=2048, n_heads=16, n_layers=4, seq_len=2048,
        batch_size=8, ffn="swiglu", attn="flash", steps=10, remat=False,
        remat_policy="full", xent_chunk=0, accum=1, optimizer="adamw"))
    out = {
        "transformer_tokens_per_sec": r["tokens_per_sec"],
        "transformer_tflops": r["tflops"],
        "transformer_peak_tflops": r["peak_tflops"],
        "transformer_mfu": r["mfu"],
        "transformer_config": r["config"],
    }
    import os

    if os.environ.get("BENCH_SKIP_BIG"):
        return out
    try:
        # the big-model bar (VERDICT r2 item 1): 1.21B params, vocab 32k,
        # f32 master weights, on ONE 16GB chip — Adafactor + bf16 +
        # dots-policy remat + chunked cross-entropy. Round 2 ran this at
        # 36.4% MFU; the round-3 recipe measures ~60%.
        rb = mfu_run(argparse.Namespace(
            vocab=32768, d_model=2048, n_heads=16, n_layers=16,
            seq_len=2048, batch_size=4, ffn="swiglu", attn="flash",
            steps=6, remat=True, remat_policy="dots", xent_chunk=1024,
            accum=1, optimizer="adafactor"))
        out.update({
            "big_model_mfu": rb["mfu"],
            "big_model_tflops": rb["tflops"],
            "big_model_tokens_per_sec": rb["tokens_per_sec"],
            "big_model_params_m": rb["config"]["params_m"],
        })
    except Exception as e:  # pragma: no cover - keep the headline robust
        out["big_model_error"] = repr(e)[:200]
    return out


def bench_kernel_numerics():
    """On-chip MOSAIC-COMPILED flash-kernel numerics gate (round 4,
    VERDICT r3 weak-3): the Pallas kernels' correctness tests run in
    interpret mode on the CPU suite; this certifies the compiled
    kernels on the real chip every bench round. Compares flash
    fwd+bwd against XLA attention (plain causal, GQA, sliding window)
    and one ring CHUNK pair (the `_chunk_fwd` + log-sum-exp merge the
    ring kernel is built from, with a nonzero global offset) at bf16
    tolerance. Returns {} off-TPU; never raises — a failure shows up
    as kernel_numerics_ok: false in the JSON line."""
    import jax
    import jax.numpy as jnp

    if jax.default_backend() != "tpu":
        return {}
    try:
        from shallowspeed_tpu.ops import flash_attention as FA
        from shallowspeed_tpu.ops.attention import attention

        rng = np.random.default_rng(7)

        def mk(b, t, h, d, kvh=None):
            kh = kvh or h
            return (jnp.asarray(rng.normal(size=(b, t, h, d)) * 0.5,
                                jnp.bfloat16),
                    jnp.asarray(rng.normal(size=(b, t, kh, d)) * 0.5,
                                jnp.bfloat16),
                    jnp.asarray(rng.normal(size=(b, t, kh, d)) * 0.5,
                                jnp.bfloat16))

        def err(a, b):
            a = np.asarray(a, np.float32)
            b = np.asarray(b, np.float32)
            scale = max(1e-6, float(np.abs(b).max()))
            return float(np.abs(a - b).max()) / scale

        def grads(f, q, k, v):
            def loss(q, k, v):
                return (f(q, k, v).astype(jnp.float32) ** 2).mean()

            return jax.grad(loss, argnums=(0, 1, 2))(q, k, v)

        # self-calibrating criterion: the bf16 flash kernel and bf16 XLA
        # attention are BOTH compared against an f32 XLA oracle; the
        # kernel passes when its error stays within a small multiple of
        # XLA-bf16's own rounding error (an absolute bf16 tolerance
        # would be a guess; this measures the rounding floor in place)
        errs = {}
        for name, kvh, w in (("causal", None, 0), ("gqa", 2, 0),
                             ("window", None, 64)):
            q, k, v = mk(2, 512, 8, 64, kvh)
            q32, k32, v32 = (x.astype(jnp.float32) for x in (q, k, v))

            def fl(q, k, v, w=w):
                return FA.flash_attention(q, k, v, causal=True, window=w)

            def xl(q, k, v, w=w):
                return attention(q, k, v, causal=True, window=w)

            oracle = [jax.jit(xl)(q32, k32, v32)]
            oracle += list(jax.jit(
                lambda q, k, v: grads(xl, q, k, v))(q32, k32, v32))
            got_f = [jax.jit(fl)(q, k, v)]
            got_f += list(jax.jit(
                lambda q, k, v: grads(fl, q, k, v))(q, k, v))
            got_x = [jax.jit(xl)(q, k, v)]
            got_x += list(jax.jit(
                lambda q, k, v: grads(xl, q, k, v))(q, k, v))
            e_f = max(err(a, o) for a, o in zip(got_f, oracle))
            e_x = max(err(a, o) for a, o in zip(got_x, oracle))
            errs[name] = {"flash": round(e_f, 5),
                          "xla_bf16_floor": round(e_x, 5)}

        # one ring chunk pair: second-half queries vs (earlier block at
        # rel=t/2, own block at rel=0), merged — the exact primitives
        # ring_flash_attention composes, compiled on this chip
        q, k, v = mk(2, 512, 8, 64)
        t2 = 256
        qh = q[:, t2:]
        (_, _, _, _, kvh_, _, bq, bk, nqb_chunk) = FA._ring_geometry(
            qh, k[:, :t2])
        # out_dtype f32: the exact chunk-output dtype the ring passes
        # (round 6 — the bf16 chunk rounding was the r5 2.3x-above-
        # floor finding; BASELINE.md 'ring-chunk numerics envelope')
        kw = dict(causal=True, window=0, bq=bq, bk=bk,
                  nqb_chunk=nqb_chunk, interpret=False,
                  out_dtype=jnp.float32)
        q3 = FA._fold_q(qh, kvh_)

        @jax.jit
        def ring_pair(q3, k, v):
            o0, l0 = FA._chunk_fwd(q3, FA._to_bhsd(k[:, :t2]),
                                   FA._to_bhsd(v[:, :t2]), t2, **kw)
            o1, l1 = FA._chunk_fwd(q3, FA._to_bhsd(k[:, t2:]),
                                   FA._to_bhsd(v[:, t2:]), 0, **kw)
            o, _ = FA._merge_chunks(o0.astype(jnp.float32), l0, o1, l1)
            return FA._unfold_q(o.astype(q3.dtype), 2, 8)

        oref32 = attention(q.astype(jnp.float32), k.astype(jnp.float32),
                           v.astype(jnp.float32), causal=True)[:, t2:]
        oref16 = attention(q, k, v, causal=True)[:, t2:]
        errs["ring_chunk"] = {
            "flash": round(err(ring_pair(q3, k, v), oref32), 5),
            "xla_bf16_floor": round(err(oref16, oref32), 5)}

        # pass = within 3x the measured XLA-bf16 rounding floor plus a
        # 0.005 absolute allowance (fwd-only cases have tiny floors)
        ok = all(e["flash"] <= 3.0 * e["xla_bf16_floor"] + 0.005
                 for e in errs.values())
        return {"kernel_numerics_ok": ok,
                "kernel_numerics_rel_err": errs}
    except Exception as e:  # pragma: no cover — never break the headline
        return {"kernel_numerics_ok": False,
                "kernel_numerics_error": repr(e)[:200]}


def bench_paged_decode_numerics():
    """Paged flash-decode kernel vs its XLA reference
    (`serving/cache.gather_table` + `kv_cache.masked_attention`) —
    the fast-decode analog of `bench_kernel_numerics`, but runnable on
    EVERY backend: interpret mode off-TPU (the exact code path the CPU
    test suite pins) and Mosaic-compiled on TPU, so every bench round
    records the kernel's numerics envelope next to the training
    kernels'. Covers causal, GQA, and int8-KV pools; errors are
    relmax vs the f32 reference, pass bar 1e-4 (the pinned parity —
    both sides compute f32 scores, so the envelope is gather/reorder
    noise, not a dtype floor). Never raises — a failure lands as
    paged_decode_numerics_ok: false."""
    import jax
    import jax.numpy as jnp

    try:
        from shallowspeed_tpu.models import transformer as T
        from shallowspeed_tpu.models.kv_cache import masked_attention
        from shallowspeed_tpu.ops.flash_attention import paged_flash_decode
        from shallowspeed_tpu.serving.cache import (gather_table,
                                                    init_block_pool,
                                                    write_rows)

        rng = np.random.default_rng(11)

        def err(a, b):
            a = np.asarray(a, np.float32)
            b = np.asarray(b, np.float32)
            return float(np.abs(a - b).max()
                         / max(1e-6, float(np.abs(b).max())))

        entries = {}
        for name, kvh, quant in (("paged_decode", 0, False),
                                 ("paged_decode_gqa", 2, False),
                                 ("paged_decode_int8", 0, True)):
            cfg = T.TransformerConfig(vocab=64, d_model=256, n_heads=4,
                                      n_kv_heads=kvh, n_layers=1,
                                      max_seq=512)
            bs, n, s, w = 16, 32, 4, 4
            pool = init_block_pool(cfg, n, bs,
                                   "int8" if quant else "")[0]
            bt = rng.integers(1, n, (s, w)).astype(np.int32)
            pos = np.asarray([bs * w - 1, 17, 40, 3], np.int32)
            for row in range(s):
                for p in range(pos[row] + 1):
                    k = jnp.asarray(rng.normal(
                        size=(1, cfg.kv_heads, cfg.head_dim)),
                        jnp.float32)
                    v = jnp.asarray(rng.normal(
                        size=(1, cfg.kv_heads, cfg.head_dim)),
                        jnp.float32)
                    pool = write_rows(pool, k, v,
                                      jnp.asarray([bt[row, p // bs]]),
                                      jnp.asarray([p % bs]), quant)
            q = jnp.asarray(rng.normal(
                size=(s, cfg.n_heads, cfg.head_dim)), jnp.float32)
            got = paged_flash_decode(q, pool, jnp.asarray(bt),
                                     jnp.asarray(pos))
            span = jnp.arange(w * bs)
            valid = (span[None, :] <= pos[:, None])[
                :, None, None, None, :]
            ref = masked_attention(q[:, None],
                                   gather_table(pool, jnp.asarray(bt)),
                                   valid, cfg)[:, 0]
            entries[name] = {"flash": round(err(got, ref), 7),
                             "ref": "gather_table+masked_attention"}
        ok = all(e["flash"] <= 1e-4 for e in entries.values())
        return {"paged_decode_numerics_ok": ok, "entries": entries}
    except Exception as e:  # pragma: no cover — keep the headline robust
        return {"paged_decode_numerics_ok": False,
                "paged_decode_error": repr(e)[:200], "entries": {}}


def overlap_case_child():
    """`bench.py --overlap-child`: the dp>1/accum>1 comm-overlap case,
    run in a fresh process whose parent configured a 2-virtual-device
    CPU platform (dp=2 needs two devices; XLA host-device flags must
    land before backend init, hence the subprocess). Trains the
    reference MLP workload with the fused dp engine, bulk reduction vs
    bucketed backward-overlapped reduction (`parallel/overlap.py`),
    and prints ONE JSON line: median samples/sec each way, the
    telemetry-measured `exposed_comm_frac` of both step programs, and
    the oracle parity (worst-leaf relmax after the timed steps)."""
    import jax

    jax.config.update("jax_platforms", "cpu")
    from shallowspeed_tpu.engine import FusedDPEngine
    from shallowspeed_tpu.models.mlp import MLPStage
    from shallowspeed_tpu.optim import SGD
    from shallowspeed_tpu.parallel.mesh import make_mesh
    from shallowspeed_tpu.parallel.overlap import (OverlapConfig,
                                                   collective_exposure)

    dp = 2
    rng = np.random.default_rng(0)
    xs = rng.normal(size=(N_MU, GBS // dp // N_MU, 784)).astype(np.float32)
    labels = rng.integers(0, 10, GBS // dp)
    ys = np.zeros((GBS // dp, 10), np.float32)
    ys[np.arange(GBS // dp), labels] = 1.0
    ys = ys.reshape(N_MU, GBS // dp // N_MU, 10)

    class _DS:
        def load_mubatch_stack(self, batch_id):
            return xs, ys

    ds = [_DS() for _ in range(dp)]

    def build(ov):
        stage = MLPStage(LAYER_SIZES, 0, 1, batch_size=GBS)
        return FusedDPEngine(stage, SGD(LR), make_mesh(dp, 1),
                             overlap=ov)

    bucket_mb = 0.25  # ~4 buckets over the reference MLP's ~0.9 MiB
    engines = {"off": build(None),
               "on": build(OverlapConfig(bucket_mb=bucket_mb))}
    for eng in engines.values():
        eng.train_batch(0, ds)  # compile warmup
        jax.device_get(eng.params[0]["b"])

    def one_round(eng, n_batches=40) -> float:
        t0 = time.perf_counter()
        for b in range(n_batches):
            eng.train_batch(b, ds)
        jax.device_get(eng.params[0]["b"])
        return n_batches * GBS / (time.perf_counter() - t0)

    meas = interleaved_medians(
        {k: (lambda e=v: one_round(e)) for k, v in engines.items()},
        rounds=5)

    parity = max(
        float(np.abs(np.asarray(a[k]) - np.asarray(b[k])).max()
              / max(1e-8, float(np.abs(np.asarray(b[k])).max())))
        for a, b in zip(engines["on"].params, engines["off"].params)
        for k in ("W", "b"))

    def exposure(eng):
        tree = jax.tree_util.tree_map(
            lambda l: jax.ShapeDtypeStruct(l.shape, l.dtype),
            (eng.params, eng.opt_state))
        data = (jax.ShapeDtypeStruct((dp, *xs.shape), np.float32),
                jax.ShapeDtypeStruct((dp, *ys.shape), np.float32))
        closed = jax.make_jaxpr(eng._step)(*tree, *data)
        return collective_exposure(closed, axes=("dp",))

    exp_on, exp_off = exposure(engines["on"]), exposure(engines["off"])
    print(json.dumps({
        "bucket_mb": bucket_mb,
        "samples_per_sec": {k: round(v["median"], 1)
                            for k, v in meas.items()},
        "spread": {k: v["spread"] for k, v in meas.items()},
        "speedup_on_vs_off": round(meas["on"]["median"]
                                   / meas["off"]["median"], 4),
        "exposed_comm_frac": {"on": exp_on["exposed_comm_frac"],
                              "off": exp_off["exposed_comm_frac"]},
        "dp_collectives": {"on": exp_on["n_collectives"],
                           "off": exp_off["n_collectives"]},
        "oracle_parity_relmax": parity,
    }))


def bench_overlap() -> dict:
    """Run the overlap case in a subprocess with a 2-virtual-device CPU
    platform (this host's TPU is one chip — dp=2 needs virtual devices,
    and XLA host-device flags are read once at backend init, which has
    long happened in the parent). Never raises — a failure lands as
    overlap_error in the JSON line."""
    import os
    import subprocess
    import sys

    env = dict(os.environ)
    env["XLA_FLAGS"] = (env.get("XLA_FLAGS", "")
                        + " --xla_force_host_platform_device_count=2"
                        ).strip()
    try:
        proc = subprocess.run(
            [sys.executable, str(Path(__file__).resolve()),
             "--overlap-child"],
            env=env, capture_output=True, text=True, timeout=900)
        line = proc.stdout.strip().splitlines()[-1]
        return {"overlap_case": json.loads(line)}
    except Exception as e:  # pragma: no cover — keep the headline robust
        return {"overlap_error": repr(e)[:200]}


def bench_attribution() -> dict:
    """Roofline waterfall of the reference MLP workload's fused step
    (shallowspeed_tpu/telemetry/attribution.py): from BENCH_r06 on the
    bench line carries its own `attrib_*` decomposition — measured
    fenced step time vs analytic compute (matmuls at the MXU peak,
    fusions at the HBM roofline; calibrated effective rates on
    non-TPU hosts) — so a throughput drop arrives with its own first
    diagnosis. Never raises — a failure lands as attribution_error."""
    import jax

    from shallowspeed_tpu import telemetry as tele
    from shallowspeed_tpu.engine import FusedDPEngine
    from shallowspeed_tpu.models.mlp import MLPStage
    from shallowspeed_tpu.optim import SGD
    from shallowspeed_tpu.parallel.mesh import make_mesh

    try:
        rng = np.random.default_rng(0)
        xs = rng.normal(size=(N_MU, GBS // N_MU, 784)).astype(np.float32)
        labels = rng.integers(0, 10, GBS)
        ys = np.zeros((GBS, 10), np.float32)
        ys[np.arange(GBS), labels] = 1.0
        ys = ys.reshape(N_MU, GBS // N_MU, 10)

        class _DS:
            def load_mubatch_stack(self, batch_id):
                return xs, ys

        ds = [_DS()]
        tracer = tele.configure(level="spans")
        try:
            stage = MLPStage(LAYER_SIZES, 0, 1, batch_size=GBS)
            eng = FusedDPEngine(stage, SGD(LR), make_mesh(1, 1))
            telem = tele.RunTelemetry(eng, tracer, dtype="f32")
            eng.train_batch(0, ds)  # compile (excluded)
            jax.device_get(eng.params[0]["b"])
            telem.step_fields()  # advance the span mark past compile
            n = 12
            t0 = time.perf_counter()
            for b in range(1, 1 + n):
                eng.train_batch(b, ds)
            jax.device_get(eng.params[0]["b"])
            window = time.perf_counter() - t0
            fields = telem.step_fields(window_secs=window,
                                       steps_in_window=n)
        finally:
            tele.configure(level="off")
        return {"attribution": {k: v for k, v in fields.items()
                                if k.startswith("attrib_")}}
    except Exception as e:  # pragma: no cover — keep the headline robust
        return {"attribution_error": repr(e)[:200]}


def bench_fp8() -> dict:
    """The fp8 attribution gate (round 18, ROADMAP item 5's rollout
    contract): the SAME small transformer train step twice — a bf16
    baseline and an `fp8_dense=True` case — each attributed by the
    roofline waterfall with its own frozen self-scale (the RunTelemetry
    protocol: window A fits `compute_scale`, window B is priced against
    the frozen value, so `attrib_unexplained_frac` measures real
    window-to-window stability, not a tautology). Quantized dense dots
    are priced at `FP8_FLOPS_RATIO` x the MXU rate with 1-byte
    operands, so the fp8-on case's `attrib_mxu_frac` must come out
    STRICTLY below the baseline's while the quantize traffic lands in
    the HBM term — the headline `fp8_mxu_shrink` (baseline mxu frac /
    fp8 mxu frac, > 1.0 when the pricing holds) joins the --regress
    trajectory gate. The line also carries the one-batch parity
    rel-err between the two cases' losses (same init, same tokens) —
    the static half of the shadow-parity envelope the runtime
    observatory (telemetry/numerics.py) enforces live. Never raises —
    a failure lands as fp8_error."""
    import jax
    import jax.numpy as jnp

    from shallowspeed_tpu.models import transformer as tf
    from shallowspeed_tpu.telemetry.attribution import (
        device_rates, roofline_of_jaxpr, roofline_seconds,
        step_waterfall)

    if tf._FP8_DTYPE is None:
        return {"fp8_error": "float8_e4m3fn unsupported in this build"}
    try:
        rng = np.random.default_rng(18)
        toks = jnp.asarray(rng.integers(0, 64, (4, 32)), jnp.int32)
        tgts = jnp.asarray(rng.integers(0, 64, (4, 32)), jnp.int32)
        rates = device_rates(dtype="f32")
        cases: dict = {}
        first_loss: dict = {}
        for name, fp8 in (("bf16", False), ("fp8", True)):
            cfg = tf.TransformerConfig(
                vocab=64, d_model=64, n_heads=4, n_layers=2, max_seq=32,
                compute_dtype=jnp.bfloat16, fp8_dense=fp8)
            params = tf.init(cfg, seed=0)

            def step(p, x, y, cfg=cfg):
                ls, g = jax.value_and_grad(tf.loss)(p, x, y, cfg)
                return ls, jax.tree_util.tree_map(
                    lambda w, gw: w - 1e-3 * gw, p, g)

            roof = roofline_of_jaxpr(
                jax.make_jaxpr(step)(params, toks, tgts))
            secs = roofline_seconds(roof, rates)
            jstep = jax.jit(step)
            ls, params = jstep(params, toks, tgts)  # compile (excluded)
            first_loss[name] = float(jax.device_get(ls))

            def window(p, n=8):
                t0 = time.perf_counter()
                for _ in range(n):
                    ls, p = jstep(p, toks, tgts)
                jax.block_until_ready(ls)
                return (time.perf_counter() - t0) / n, p

            t_a, params = window(params)    # fits the self-scale ...
            scale = t_a / max(secs["mxu_s"] + secs["hbm_s"], 1e-12)
            t_b, params = window(params)    # ... window B runs frozen
            fields = step_waterfall(t_b, roofline=roof, rates=rates,
                                    compute_scale=scale)
            fields["fp8_dot_flops"] = int(roof["flops_fp8_shard"]
                                          + roof["flops_fp8_global"])
            cases[name] = fields
        shrink = (cases["bf16"]["attrib_mxu_frac"]
                  / max(cases["fp8"]["attrib_mxu_frac"], 1e-9))
        parity = (abs(first_loss["fp8"] - first_loss["bf16"])
                  / max(abs(first_loss["bf16"]), 1e-12))
        return {"fp8_mxu_shrink": round(shrink, 4),
                "fp8_attribution": {
                    **cases,
                    "parity_loss_rel": round(parity, 6)}}
    except Exception as e:  # pragma: no cover — keep the headline robust
        return {"fp8_error": repr(e)[:200]}


def bench_serving() -> dict:
    """Offered-load sweep of the serving runtime (round 11,
    `shallowspeed_tpu/serving/`): a small transformer served at
    increasing concurrency, recording per level the aggregate decode
    tok/s and p50 ttft/tpot from the engine's own schema-v6 request
    records. The headline `serving_tok_per_sec` (best level) enters
    the `--regress` noise-band gate; per-level latencies show the
    throughput/latency trade the continuous batch makes as offered
    load grows. Runs identically on CPU and TPU (the compiled tick is
    platform-agnostic); never raises — a failure lands as
    serving_error in the JSON line."""
    import jax

    from shallowspeed_tpu.models import transformer as T
    from shallowspeed_tpu.serving import ServingEngine

    try:
        cfg = T.TransformerConfig(vocab=128, d_model=64, n_heads=4,
                                  n_layers=2, max_seq=256)
        params = jax.device_put(T.init(cfg, seed=0))
        lens = [8, 20, 33, 48]
        max_new = 24

        def build(spec_k=0):
            return ServingEngine(params, cfg, n_blocks=96,
                                 block_size=16, max_slots=8,
                                 prefill_chunk=32, spec_k=spec_k)

        def prompt(i):
            # self-similar prompts (a repeated motif): the spec-on
            # sweep's n-gram proposer needs repetition to draft from,
            # like real templated/code traffic. Seeded per request id
            # — NOT the shared rng — so spec-on and spec-off levels
            # serve byte-identical prompts and compare fairly
            t = lens[i % len(lens)]
            motif = np.random.default_rng([7, i]).integers(
                0, cfg.vocab, max(2, t // 3)).astype(np.int32)
            reps = -(-t // motif.shape[0])
            return np.concatenate([motif] * reps)[:t]

        def offer(eng, n):
            for i in range(n):
                eng.submit(prompt(i), max_new, rid=f"l{n}_{i}")
            t0 = time.perf_counter()
            eng.run()
            wall = time.perf_counter() - t0
            toks = sum(r["tokens_out"] for r in eng.request_records)
            p50 = lambda k: float(np.median(  # noqa: E731
                [r[k] for r in eng.request_records if k in r]))
            # lifecycle phase accounting (round 13): p50 time from
            # admission to the first decode — the prefill share of
            # ttft, split out from queueing (wait_ms covers that)
            prefill = [
                next(p["wall"] for p in tl if p["phase"] == "decoding")
                - next(p["wall"] for p in tl if p["phase"] == "admitted")
                for tl in eng.timelines.values()
                if any(p["phase"] == "decoding" for p in tl)]
            # waterfall components (round 16): the same phase ->
            # rq_* mapping the stitcher and the live monitor use,
            # reduced over the retained timelines — the serving
            # sweep's latency now names where it goes per level
            from shallowspeed_tpu.telemetry.tracing import (
                PHASE_COMPONENT)

            comp_ms = {"rq_queue": [], "rq_prefill": [],
                       "rq_decode": []}
            for tl in eng.timelines.values():
                by = {}
                for a, b in zip(tl, tl[1:]):
                    c = PHASE_COMPONENT.get(a["phase"])
                    if c in comp_ms:
                        by[c] = by.get(c, 0.0) \
                            + (b["wall"] - a["wall"]) * 1e3
                for c, v in by.items():
                    comp_ms[c].append(v)
            # capacity accounting (round 20, the memory observatory):
            # generated tokens per PEAK live KV block — how much decode
            # work each resident block bought at this offered load. A
            # drop with tok/s flat means residency grew (blocks pinned
            # longer or admission overcommitting), which throughput
            # alone cannot see.
            peak_blk = max(1, eng.alloc.peak_live)
            out = {"offered": n, "wall_s": round(wall, 3),
                   "tok_per_sec": round(toks / wall, 2),
                   "peak_live_blocks": eng.alloc.peak_live,
                   "tok_per_blk": round(toks / peak_blk, 3),
                   "ttft_p50_ms": round(p50("ttft_ms"), 2),
                   "tpot_p50_ms": round(p50("tpot_ms"), 2),
                   "prefill_p50_ms": round(
                       float(np.median(prefill)) * 1e3, 2)
                   if prefill else None}
            for c, vals in comp_ms.items():
                if vals:
                    out[f"{c}_p50_ms"] = round(
                        float(np.median(vals)), 2)
            if eng.spec_k:
                d = eng.counters["spec_drafted"]
                out["ticks"] = eng.counters["ticks"]
                out["spec_drafted"] = d
                out["spec_accepted"] = eng.counters["spec_accepted"]
                out["spec_accept_rate"] = round(
                    eng.counters["spec_accepted"] / d, 4) if d else 0.0
            return out

        # compile warmup (excluded): n=4 walks the tick through BOTH
        # table-width buckets the levels use (W=4 early, W=8 once the
        # longest prompt's table grows past 4 blocks)
        offer(build(), 4)
        # spec-on/off sweep at identical offered load: speculation
        # amortizes the per-tick weight sweep over accepted drafts in
        # otherwise-empty rows, and the streams are token-identical
        # by construction — so tok/s is directly comparable
        levels = [offer(build(), n) for n in (1, 4, 8)]
        spec_levels = [offer(build(spec_k=4), n) for n in (1, 4, 8)]
        # the headline keeps its spec-OFF contract (best gather-path
        # level, the round-11 metric --regress has banded since r07);
        # the spec-on sweep gets its OWN gated headline so neither
        # path's regression can hide behind the other's speedup
        return {"serving_case": {"levels": levels,
                                 "spec_levels": spec_levels,
                                 "block_size": 16, "slots": 8,
                                 "prefill_chunk": 32, "spec_k": 4},
                "serving_tok_per_sec": max(lv["tok_per_sec"]
                                           for lv in levels),
                # capacity headline for --regress (round 20): best
                # spec-off tokens-per-peak-live-block across levels
                "serving_capacity_tok_per_blk": max(
                    lv["tok_per_blk"] for lv in levels),
                "serving_spec_tok_per_sec": max(
                    lv["tok_per_sec"] for lv in spec_levels),
                "serving_spec_accept_rate": round(
                    sum(lv["spec_accepted"] for lv in spec_levels)
                    / max(1, sum(lv["spec_drafted"]
                                 for lv in spec_levels)), 4)}
    except Exception as e:  # pragma: no cover — keep the headline robust
        return {"serving_error": repr(e)[:200]}


def bench_fleet() -> dict:
    """Fleet offered-load sweep (round 15, `serving/router.py`): the
    SLO-aware router over TWO in-process `ServingEngine` replicas,
    served the same self-similar request mix as `bench_serving` at
    increasing offered load. Records per level the aggregate fleet
    decode tok/s and the router-observed (fleet-edge) p50 ttft; the
    headline `fleet_tok_per_sec` (best level) joins the `--regress`
    noise-band gate next to the single-engine `serving_tok_per_sec`,
    so routing overhead that starts eating the fleet's throughput
    fails the gate even when each engine alone still benches clean.
    In-process replicas keep the bench robust (no subprocess spawn
    variance); the dispatch/failover/scale logic exercised is the
    same code the cross-process driver runs. Never raises — a failure
    lands as fleet_error in the JSON line."""
    import jax

    from shallowspeed_tpu.models import transformer as T
    from shallowspeed_tpu.serving import ServingEngine
    from shallowspeed_tpu.serving.router import InProcessReplica, Router

    try:
        cfg = T.TransformerConfig(vocab=128, d_model=64, n_heads=4,
                                  n_layers=2, max_seq=256)
        params = jax.device_put(T.init(cfg, seed=0))
        lens = [8, 20, 33, 48]
        max_new = 24

        def factory(name):
            return ServingEngine(params, cfg, n_blocks=96,
                                 block_size=16, max_slots=8,
                                 prefill_chunk=32)

        def prompt(i):
            t = lens[i % len(lens)]
            motif = np.random.default_rng([11, i]).integers(
                0, cfg.vocab, max(2, t // 3)).astype(np.int32)
            reps = -(-t // motif.shape[0])
            return np.concatenate([motif] * reps)[:t]

        def offer(n):
            router = Router(
                lambda name: InProcessReplica(name, factory),
                n_replicas=2, request_timeout=120.0)
            for i in range(n):
                router.submit(prompt(i), max_new, rid=f"f{n}_{i}")
            t0 = time.perf_counter()
            router.run(max_wall=300.0)
            wall = time.perf_counter() - t0
            toks = sum(r["tokens_out"] for r in router.records
                       if r["status"] == "done")
            ttfts = [r["ttft_ms"] for r in router.records
                     if "ttft_ms" in r]
            return {"offered": n, "wall_s": round(wall, 3),
                    "tok_per_sec": round(toks / wall, 2),
                    "ttft_p50_ms": round(float(np.median(ttfts)), 2)
                    if ttfts else None,
                    "routes": router.counters["routes"]}

        offer(4)                     # compile warmup (excluded)
        levels = [offer(n) for n in (2, 8, 16)]
        return {"fleet_case": {"levels": levels, "replicas": 2,
                               "block_size": 16, "slots": 8},
                "fleet_tok_per_sec": max(lv["tok_per_sec"]
                                         for lv in levels)}
    except Exception as e:  # pragma: no cover — keep the headline robust
        return {"fleet_error": repr(e)[:200]}


def bench_prefix() -> dict:
    """Shared-prompt prefix-caching sweep (round 19,
    `serving/cache.PrefixIndex` + sticky routing). Three measurements:
    (1) walker-measured prefill FLOPs — with `prefill_chunk ==
    block_size` the chunk-call count maps 1:1 to blocks prefilled, so
    pricing one chunk's jaxpr (`roofline_of_jaxpr`) and counting chunk
    calls gives the exact prefill FLOPs a fully-shared prompt pays
    cold vs on a cache hit (the hit must drop to the copied TAIL block
    only); (2) stream parity — the prefix-on engine must emit
    token-identical streams to the prefix-OFF oracle over a mixed
    greedy/sampled shared-prompt batch; (3) the 2-replica sticky
    on/off fleet sweep — same shared-prefix request mix, sticky
    routing on vs off (prefix caching ON in both fleets), recording
    fleet-edge ttft p50 per level. Headline `prefix_tok_per_sec`
    (best sticky-on level) joins the `--regress` noise-band gate.
    Never raises — a failure lands as prefix_error in the JSON
    line."""
    import jax

    from shallowspeed_tpu.models import transformer as T
    from shallowspeed_tpu.serving import ServingEngine
    from shallowspeed_tpu.serving.cache import blocks_for
    from shallowspeed_tpu.serving.engine import _prefill_chunk, table_width
    from shallowspeed_tpu.serving.router import InProcessReplica, Router
    from shallowspeed_tpu.telemetry.attribution import roofline_of_jaxpr

    try:
        cfg = T.TransformerConfig(vocab=128, d_model=64, n_heads=4,
                                  n_layers=2, max_seq=256)
        params = jax.device_put(T.init(cfg, seed=0))
        bs = 16                      # block_size == prefill_chunk
        shared_len, tail_len, max_new = 96, 9, 8
        n_fam = 8                    # distinct shared preambles

        def family(f):
            return np.random.default_rng([19, f]).integers(
                0, cfg.vocab, shared_len).astype(np.int32)

        def prompt(f, i):
            tail = np.random.default_rng([23, f, i]).integers(
                0, cfg.vocab, tail_len).astype(np.int32)
            return np.concatenate([family(f), tail])

        def build(prefix):
            return ServingEngine(params, cfg, n_blocks=96,
                                 block_size=bs, max_slots=8,
                                 prefill_chunk=bs, prefix_cache=prefix)

        # (1) FLOPs per prefill chunk, priced off the traced program
        nb = blocks_for(shared_len + tail_len + max_new - 1, bs)
        w = table_width(nb, 4)
        pools = build(False).pools
        roof = roofline_of_jaxpr(jax.make_jaxpr(
            lambda *a: _prefill_chunk(*a, cfg=cfg))(
                params, pools, np.zeros((1, bs), np.int32), np.int32(0),
                np.int32(bs), np.full((1, w), 0, np.int32), np.int32(0),
                np.int32(0)))
        chunk_flops = int(roof["flops_shard"] + roof["flops_global"])
        # a fully-shared (block-aligned) prompt: cold pays every block,
        # the hit re-prefills only the copied tail block
        eng = build(True)
        full = family(0)                         # 96 tokens, 6 blocks
        eng.submit(full, max_new, rid="cold")
        eng.run()
        chunks_cold = eng.counters["prefill_chunks"]
        eng.submit(full, max_new, seed=1, rid="hit")
        eng.run()
        chunks_hit = eng.counters["prefill_chunks"] - chunks_cold

        # (2) parity: prefix-on streams vs the prefix-OFF oracle over
        # a mixed greedy/sampled shared-prompt batch
        def serve(prefix):
            e = build(prefix)
            for i in range(12):
                e.submit(prompt(i % n_fam, i // n_fam), max_new,
                         temperature=0.8 if i % 2 else 0.0, seed=i,
                         rid=f"p{i}")
            return e.run(), e
        got, eng_on = serve(True)
        ref, _ = serve(False)
        parity = all(np.array_equal(ref[k], got[k]) for k in ref)

        # (3) sticky on/off fleet sweep: 2 replicas, prefix caching ON
        # in both — only the routing differs. Arrivals come in WAVES
        # (one request per family per wave, drained between waves) —
        # the recurring shared-prompt traffic the cache targets:
        # donation happens at finish, so a family's later arrivals can
        # only hit where its earlier ones already completed. Sticky
        # keeps each family on its home replica (one cold prefill per
        # family fleet-wide); load-only routing re-pays the cold
        # prefill wherever the family lands next. The per-wave family
        # order ROTATES — with a fixed order the load tie-break is
        # deterministic and re-lands every family on the same replica
        # each wave, silently handing the off-mode full cache affinity
        # too.
        def offer(sticky, waves):
            router = Router(
                lambda name: InProcessReplica(name,
                                              lambda nm: build(True)),
                n_replicas=2, request_timeout=120.0,
                sticky=sticky, sticky_block=bs)
            t0 = time.perf_counter()
            for w in range(waves):
                for k in range(n_fam):
                    f = (k + w) % n_fam
                    router.submit(prompt(f, w), max_new,
                                  rid=f"s{waves}_{w}_{f}")
                router.run(max_wall=300.0)
            wall = time.perf_counter() - t0
            toks = sum(r["tokens_out"] for r in router.records
                       if r["status"] == "done")
            ttfts = [r["ttft_ms"] for r in router.records
                     if "ttft_ms" in r]
            return {"offered": waves * n_fam, "wall_s": round(wall, 3),
                    "tok_per_sec": round(toks / wall, 2),
                    "ttft_p50_ms": round(float(np.median(ttfts)), 2)
                    if ttfts else None}

        offer(True, 1)               # compile warmup (excluded)
        on_levels = [offer(True, n) for n in (2, 3)]
        off_levels = [offer(False, n) for n in (2, 3)]
        return {"prefix_case": {
                    "chunk_flops": chunk_flops,
                    "prefill_flops_cold": chunk_flops * chunks_cold,
                    "prefill_flops_hit": chunk_flops * chunks_hit,
                    "chunks_cold": chunks_cold,
                    "chunks_hit": chunks_hit,
                    "parity": bool(parity),
                    "skipped_tokens": int(
                        eng_on.counters["prefix_skipped_tokens"]),
                    "sticky_on": on_levels, "sticky_off": off_levels,
                    "block_size": bs, "families": n_fam,
                    "shared_len": shared_len},
                "prefix_tok_per_sec": max(lv["tok_per_sec"]
                                          for lv in on_levels),
                "prefix_sticky_ttft_p50_ms": min(
                    lv["ttft_p50_ms"] for lv in on_levels),
                "prefix_nosticky_ttft_p50_ms": min(
                    lv["ttft_p50_ms"] for lv in off_levels)}
    except Exception as e:  # pragma: no cover — keep the headline robust
        return {"prefix_error": repr(e)[:200]}


def bench_profile_overhead(rounds: int = 5) -> dict:
    """Profiler-on vs profiler-off serving throughput, INTERLEAVED
    (round 17, telemetry/profiler): each round serves the identical
    self-similar request set through a warm `ServingEngine` twice —
    once under the always-on host sampler at its default rate, once
    without — and the medians' ratio is the plane's overhead. The
    interleaving puts load transients on both sides of the ratio
    (`interleaved_medians`); BASELINE.md bands the acceptance at ±7%.
    NOT on the default bench line (`python bench.py
    --profile-overhead`) so the --regress trajectory keys stay
    stable. Never raises — failures land as profile_overhead_error."""
    import jax

    from shallowspeed_tpu.models import transformer as T
    from shallowspeed_tpu.serving import ServingEngine
    from shallowspeed_tpu.telemetry.profiler import (DEFAULT_HZ,
                                                     SamplingProfiler)

    try:
        cfg = T.TransformerConfig(vocab=128, d_model=64, n_heads=4,
                                  n_layers=2, max_seq=256)
        params = jax.device_put(T.init(cfg, seed=0))
        lens = [8, 20, 33, 48]
        max_new = 24
        offered = 8

        def prompt(i):
            t = lens[i % len(lens)]
            motif = np.random.default_rng([7, i]).integers(
                0, cfg.vocab, max(2, t // 3)).astype(np.int32)
            reps = -(-t // motif.shape[0])
            return np.concatenate([motif] * reps)[:t]

        def run_once(profiled: bool) -> float:
            eng = ServingEngine(params, cfg, n_blocks=96,
                                block_size=16, max_slots=8,
                                prefill_chunk=32)
            for i in range(offered):
                eng.submit(prompt(i), max_new, rid=f"p{i}")
            prof = SamplingProfiler().start() if profiled else None
            t0 = time.perf_counter()
            eng.run()
            wall = time.perf_counter() - t0
            if prof is not None:
                prof.stop()
            toks = sum(r["tokens_out"] for r in eng.request_records)
            return toks / wall

        run_once(False)       # compile warmup (excluded)
        meas = interleaved_medians(
            {"off": lambda: run_once(False),
             "on": lambda: run_once(True)}, rounds=rounds)
        on, off = meas["on"]["median"], meas["off"]["median"]
        return {"profile_overhead_case": {
                    "hz": DEFAULT_HZ, "offered": offered,
                    "tok_per_sec_off": round(off, 2),
                    "tok_per_sec_on": round(on, 2),
                    "rounds": {k: v["rounds"] for k, v in meas.items()},
                    "spread": {k: v["spread"] for k, v in meas.items()},
                },
                # on/off: 1.0 = free, 0.93 = the 7% band edge
                "profile_overhead_ratio": round(on / off, 4)}
    except Exception as e:  # pragma: no cover — keep the bench robust
        return {"profile_overhead_error": repr(e)[:200]}


def pinned_baseline() -> float | None:
    """The once-recorded NumPy throughput (BASELINE.json) — the stable
    denominator for vs_baseline (VERDICT r1: a re-measured baseline made
    the headline ratio noise under host load)."""
    path = Path(__file__).resolve().parent / "BASELINE.json"
    try:
        rec = json.loads(path.read_text()).get("pinned_numpy_baseline")
        return float(rec["samples_per_sec"]) if rec else None
    except (OSError, ValueError, KeyError):
        return None


def main():
    rng = np.random.default_rng(0)
    xs = rng.normal(size=(N_MU, GBS // N_MU, 784)).astype(np.float32)
    labels = rng.integers(0, 10, GBS)
    ys = np.zeros((GBS, 10), np.float32)
    ys[np.arange(GBS), labels] = 1.0
    ys = ys.reshape(N_MU, GBS // N_MU, 10)

    load_before = host_load_diagnostics()
    meas = interleaved_medians({
        "tpu": tpu_round_fn(xs, ys),
        "numpy": numpy_round_fn(xs, ys),
    }, rounds=7, gate=("tpu",))
    load_after = host_load_diagnostics(self_load=1.0)
    tpu_sps = meas["tpu"]["median"]
    np_live = meas["numpy"]["median"]
    np_pinned = pinned_baseline()

    out = {
        "metric": "mnist_mlp_train_throughput",
        "value": round(tpu_sps, 1),
        "unit": "samples/sec",
        "vs_baseline": round(tpu_sps / (np_pinned or np_live), 2),
        "baseline_pinned": np_pinned is not None,
        "numpy_live_sps": round(np_live, 1),
        # load-robustness record (VERDICT r5 weak #1): every round,
        # both spreads, and who else was on the host — a bench line
        # captured under load is now self-describing
        "rounds": {k: v["rounds"] for k, v in meas.items()},
        "spread": {k: v["spread"] for k, v in meas.items()},
        "host_load": load_before,
        "host_load_after": load_after,
        "idle_host": bool(load_before["idle_host"]
                          and load_after["idle_host"]),
    }
    out.update(bench_transformer_mfu())
    out.update(bench_kernel_numerics())
    # paged flash-decode numerics run on EVERY backend (interpret mode
    # off-TPU); its entries join the same kernel_numerics_rel_err block
    pg = bench_paged_decode_numerics()
    entries = pg.pop("entries", {})
    if entries:
        out.setdefault("kernel_numerics_rel_err", {}).update(entries)
    out.update(pg)
    out.update(bench_overlap())
    out.update(bench_attribution())
    out.update(bench_fp8())
    out.update(bench_serving())
    out.update(bench_fleet())
    out.update(bench_prefix())
    print(json.dumps(out))


if __name__ == "__main__":
    import sys

    if "--overlap-child" in sys.argv[1:]:
        overlap_case_child()
    elif "--profile-overhead" in sys.argv[1:]:
        # standalone measurement (BASELINE.md's profiler-overhead
        # record) — deliberately NOT part of the default bench line,
        # whose keys the --regress trajectory gate bands
        out = {"host_load": host_load_diagnostics()}
        out.update(bench_profile_overhead())
        print(json.dumps(out))
    else:
        main()
